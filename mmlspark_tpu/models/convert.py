"""Import trained weights from standard formats into NNFunction.

Capability parity with the reference's pretrained-model story: its
`ModelDownloader` serves *trained* CNTK nets whose value is transfer
learning through `ImageFeaturizer` (`ModelDownloader.scala:54,124`,
`ImageFeaturizer.scala:36,129-176`). The CNTK graph format died with
CNTK; the standard trained formats today are torch ``state_dict``s and
flax/orbax pytrees, so those are the importers here. GBDT interop has
the same shape (`gbdt/lgbm_compat.py` imports genuine LightGBM dumps).

Torch import contract (``import_torch_state_dict``): the source module
must define its submodules in **forward-call order** and mirror the
target architecture layer-for-layer (same convs/norms/denses, same
widths). Tensors are mapped positionally with layout transforms:

- ``Conv2d.weight`` (O, I, kH, kW) -> flax ``Conv.kernel`` (kH, kW, I, O)
- ``Linear.weight`` (O, I) -> flax ``Dense.kernel`` (I, O)
- 1-D tensors (norm scales/biases, linear biases) copy through

BatchNorm cannot be represented in the GroupNorm architectures this
framework ships (BN inference depends on ``running_mean/var``, which
have no GroupNorm equivalent). State dicts containing running stats —
or norm layers named like BatchNorm — are rejected; a BN layer with
``track_running_stats=False`` and an innocuous name is shape-identical
to GroupNorm and CANNOT be detected from tensors alone, so always
verify a converted model against the source's outputs (the pattern the
tests use) before publishing it.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import numpy as np

from mmlspark_tpu.models.function import (
    NNFunction, flatten_params, unflatten_params,
)


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor, no torch import needed here
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def import_torch_state_dict(state_dict: Dict[str, Any], arch: Dict[str, Any],
                            input_shape: Sequence[int]) -> NNFunction:
    """Convert a torch ``state_dict`` into an :class:`NNFunction`.

    ``arch``/``input_shape`` name the target architecture; the source
    module must mirror it in forward-call order (see module docstring).
    Golden-parity is tested against torch itself in
    ``tests/test_convert.py`` (same input → same logits).
    """
    import re
    bn_keys = [k for k in state_dict
               if k.endswith(("running_mean", "running_var"))
               or re.search(r"(^|\.)(bn\d*|batch_?norm\w*)\.", k)]
    if bn_keys:
        raise ValueError(
            "state_dict appears to contain BatchNorm layers "
            f"({bn_keys[:3]}...): BN inference semantics cannot be "
            "represented in this GroupNorm architecture; export a "
            "GroupNorm variant of the model instead. (Note: a stats-free "
            "BN with a non-standard name is undetectable from tensors — "
            "always verify converted outputs against the source model.)")

    src = [(k, _to_numpy(v)) for k, v in state_dict.items()
           if not k.endswith("num_batches_tracked")]

    target = NNFunction.init(arch, input_shape=input_shape, seed=0)
    flat = flatten_params(target.params)
    if len(src) != len(flat):
        raise ValueError(
            f"tensor count mismatch: state_dict has {len(src)} tensors, "
            f"architecture {arch.get('builder')!r} expects {len(flat)} "
            f"({list(flat)[:4]}...)")

    out: Dict[str, np.ndarray] = {}
    for (torch_key, t), (flax_key, ref) in zip(src, flat.items()):
        if t.ndim == 4:            # conv kernel OIHW -> HWIO
            t = np.transpose(t, (2, 3, 1, 0))
        elif t.ndim == 2:          # linear weight (O, I) -> (I, O)
            t = np.transpose(t, (1, 0))
        if t.shape != ref.shape:
            raise ValueError(
                f"shape mismatch at {torch_key!r} -> {flax_key!r}: "
                f"got {t.shape} (after layout transform), architecture "
                f"expects {ref.shape} — source layers must mirror the "
                f"target in forward-call order")
        out[flax_key] = t.astype(ref.dtype)
    return NNFunction(arch=dict(arch), params=unflatten_params(out))


def import_flax_params(params: Any, arch: Dict[str, Any],
                       input_shape: Sequence[int]) -> NNFunction:
    """Adopt an externally trained flax params pytree (e.g. restored from
    an orbax checkpoint), validating every leaf shape against ``arch``."""
    target = NNFunction.init(arch, input_shape=input_shape, seed=0)
    ref = flatten_params(target.params)
    got = flatten_params(params)
    if set(ref) != set(got):
        missing = sorted(set(ref) - set(got))[:4]
        extra = sorted(set(got) - set(ref))[:4]
        raise ValueError(f"param tree mismatch: missing={missing} "
                         f"extra={extra}")
    for k in ref:
        if ref[k].shape != got[k].shape:
            raise ValueError(f"shape mismatch at {k!r}: got "
                             f"{got[k].shape}, expected {ref[k].shape}")
    return NNFunction(
        arch=dict(arch),
        params=unflatten_params(
            {k: np.asarray(v, dtype=ref[k].dtype) for k, v in got.items()}))
