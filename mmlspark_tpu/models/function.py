"""NNFunction: the framework's deep-net model format.

Capability parity with the reference's CNTK evaluation engine surface
(`cntk-model/src/main/scala/SerializableFunction.scala:25-85`,
`CNTKModel.scala:30-69`): a serialized network that can be loaded,
evaluated with feed/fetch-dict semantics, truncated at a named layer
(for transfer learning), and shipped inside a pipeline stage.

TPU-native design: the network is a flax ``LayeredModel`` — an ordered
list of named layers — whose forward pass is a pure jitted function; the
"serialized model" is an architecture config (JSON) + a params pytree
(npz), so persistence is exact and rebuildable. Layer truncation is a
static argument, giving each cut its own fused XLA program.
"""

from __future__ import annotations

import dataclasses
import inspect
import json
import os
import typing
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import flax.linen as nn


def _wants_train_flag(layer) -> bool:
    try:
        sig = inspect.signature(layer.__call__ if isinstance(layer, nn.Module)
                                else layer)
        return "train" in sig.parameters
    except (TypeError, ValueError):
        return False


class LayeredModel(nn.Module):
    """Ordered named layers with truncation at any name.

    ``layers`` is a tuple of (name, module-or-callable). Residual wiring
    lives inside block modules; the top level stays a linear chain so a
    named cut point exists between any two blocks (parity: CNTK
    ``layerNames`` + output-node selection, `Schema.scala:54-74`).
    """

    layers: Tuple[Tuple[str, Any], ...]

    @property
    def layer_names(self) -> List[str]:
        return [name for name, _ in self.layers]

    @nn.compact
    def __call__(self, x, output_layer: Optional[str] = None,
                 train: bool = False):
        if output_layer is not None and output_layer not in self.layer_names:
            raise KeyError(f"no layer named {output_layer!r}; "
                           f"have {self.layer_names}")
        for name, layer in self.layers:
            if _wants_train_flag(layer):
                x = layer(x, train=train)
            else:
                x = layer(x)
            if output_layer is not None and name == output_layer:
                return x
        return x


@dataclasses.dataclass
class NNFunction:
    """A loadable/evaluable network: architecture config + params pytree.

    ``arch`` is a JSON-able dict whose ``builder`` key names a registered
    architecture factory (see :mod:`mmlspark_tpu.models.resnet`), so a
    checkpoint fully reconstructs the module — the analogue of loading a
    serialized CNTK Function from bytes.
    """

    arch: Dict[str, Any]
    params: Any

    # class-level registry of architecture builders (not a dataclass field)
    _BUILDERS: typing.ClassVar[Dict[str, Callable[..., nn.Module]]] = {}

    @classmethod
    def register_builder(cls, name: str):
        def deco(fn):
            cls._BUILDERS[name] = fn
            return fn
        return deco

    def module(self) -> nn.Module:
        builder = NNFunction._BUILDERS.get(self.arch["builder"])
        if builder is None:
            raise KeyError(f"unknown architecture builder "
                           f"{self.arch['builder']!r}; registered: "
                           f"{sorted(NNFunction._BUILDERS)}")
        kwargs = {k: v for k, v in self.arch.items() if k != "builder"}
        return builder(**kwargs)

    # -- evaluation ---------------------------------------------------------

    @property
    def layer_names(self) -> List[str]:
        return list(self.module().layer_names)

    def apply(self, x, output_layer: Optional[str] = None,
              train: bool = False):
        """Forward pass; ``output_layer`` truncates at a named layer."""
        return self.module().apply(self.params, x, output_layer=output_layer,
                                   train=train)

    def layer_name_for_cut(self, cut_layers: int) -> Optional[str]:
        """Name of the output layer after cutting the last ``cut_layers``
        layers (parity: ImageFeaturizer.setCutOutputLayers)."""
        names = self.layer_names
        if not 0 <= cut_layers < len(names):
            raise ValueError(f"cut_layers={cut_layers} out of range for "
                             f"{len(names)} layers")
        return None if cut_layers == 0 else names[len(names) - 1 - cut_layers]

    # -- persistence --------------------------------------------------------

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "arch.json"), "w") as f:
            json.dump(self.arch, f, indent=2)
        np.savez_compressed(os.path.join(path, "params.npz"),
                            **flatten_params(self.params))

    @staticmethod
    def load(path: str) -> "NNFunction":
        with open(os.path.join(path, "arch.json")) as f:
            arch = json.load(f)
        with np.load(os.path.join(path, "params.npz")) as npz:
            params = unflatten_params({k: npz[k] for k in npz.files})
        return NNFunction(arch=arch, params=params)

    @staticmethod
    def init(arch: Dict[str, Any], input_shape: Sequence[int],
             seed: int = 0) -> "NNFunction":
        """Random-init an architecture (the training entry point)."""
        import jax
        fn = NNFunction(arch=arch, params=None)
        module = fn.module()
        dummy = np.zeros((1, *input_shape), dtype=np.float32)
        fn.params = module.init(jax.random.PRNGKey(seed), dummy)
        return fn


def flatten_params(params, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in params.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten_params(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def unflatten_params(flat: Dict[str, np.ndarray]):
    root: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return root
