from mmlspark_tpu.models.function import NNFunction, LayeredModel
from mmlspark_tpu.models.nn import NNModel
from mmlspark_tpu.models.resnet import ResNet, ConvNet, cifar_resnet, cifar_convnet
from mmlspark_tpu.models.featurizer import ImageFeaturizer
from mmlspark_tpu.models.trainer import NNLearner
from mmlspark_tpu.models.zoo import ModelDownloader, ModelRepo, ModelSchema
from mmlspark_tpu.models.transformer import (
    TransformerConfig,
    build_spmd_train_step,
    init_params as init_transformer_params,
    shard_params as shard_transformer_params,
    reference_logits,
    restore_train_state,
    save_train_state,
)

__all__ = ["NNFunction", "LayeredModel", "NNModel", "NNLearner", "ResNet",
           "ConvNet", "cifar_resnet", "cifar_convnet", "ImageFeaturizer",
           "ModelDownloader", "ModelRepo", "ModelSchema",
           "TransformerConfig", "build_spmd_train_step",
           "init_transformer_params", "shard_transformer_params",
           "reference_logits", "restore_train_state", "save_train_state"]
