from mmlspark_tpu.models.function import NNFunction, LayeredModel
from mmlspark_tpu.models.nn import NNModel
from mmlspark_tpu.models.resnet import ResNet, ConvNet, cifar_resnet, cifar_convnet
from mmlspark_tpu.models.featurizer import ImageFeaturizer
from mmlspark_tpu.models.trainer import NNLearner
from mmlspark_tpu.models.zoo import ModelDownloader, ModelRepo, ModelSchema

__all__ = ["NNFunction", "LayeredModel", "NNModel", "NNLearner", "ResNet",
           "ConvNet", "cifar_resnet", "cifar_convnet", "ImageFeaturizer",
           "ModelDownloader", "ModelRepo", "ModelSchema"]
