"""NNLearner: in-process data-parallel deep-net training on the mesh.

Capability parity with `src/cntk-train` (`CNTKLearner.scala:85-190`): an
Estimator that takes a labeled frame, trains a network with configurable
loss/optimizer/schedule (the role BrainScript configs play), and returns
an ``NNModel`` for scoring. The reference's entire data-export ->
ssh/scp -> `mpirun cntk` -> copy-model-back chain
(`CommandBuilders.scala:149-266`) collapses to a jitted train step with
sharding-induced ICI allreduce — zero processes, zero sockets, zero MPI.

Distribution: batches are sharded over the mesh's ``data`` axis
(per-host input sharding on a multi-process runtime — each host feeds
only its rows); params and optimizer state are replicated, or sharded
over ``model`` for tensor parallelism when ``mesh_shape`` names a
``model`` axis (:mod:`mmlspark_tpu.parallel.dist` owns the sharding
rule; XLA/GSPMD inserts the gradient allreduce and the TP collectives
from the ``NamedSharding`` annotations, and the train state is donated
through every step so the optimizer update lands in place). Step
checkpointing uses the native sharded store
(:mod:`mmlspark_tpu.io.checkpoint`): each device writes its own
shards, and a resume may use a different topology than the save.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    Param, HasLabelCol, HasFeaturesCol, in_set, in_range,
)
from mmlspark_tpu.core.stage import Estimator
from mmlspark_tpu.models.function import NNFunction
from mmlspark_tpu.models.nn import NNModel
from mmlspark_tpu.parallel import MeshSpec, build_mesh, pad_to_multiple

LOSSES = ("softmax_cross_entropy", "sigmoid_cross_entropy", "squared_error")
OPTIMIZERS = ("sgd", "momentum", "adam", "adamw")

_TRAINER_METRICS = None


def _metrics():
    """Process-registry training telemetry, bound lazily so importing
    the trainer costs nothing."""
    global _TRAINER_METRICS
    if _TRAINER_METRICS is None:
        from mmlspark_tpu.core.telemetry import REGISTRY, log_buckets
        _TRAINER_METRICS = {
            "step_ms": REGISTRY.histogram(
                "trainer_step_ms",
                "Host-loop wall-clock per train step (dispatch is "
                "async: mostly host+transfer time, with periodic "
                "device blocks when the in-flight window fills)."),
            "examples_per_sec": REGISTRY.histogram(
                "trainer_examples_per_sec",
                "Real (unpadded) examples per second per host-loop "
                "step.", buckets=log_buckets(1.0, 1e7)),
            # wider ladder than the request-latency default: a
            # multi-GB save/restore routinely takes 30-120 s, and a
            # 10 s top edge would collapse every sample into +Inf
            "ckpt_save_ms": REGISTRY.histogram(
                "trainer_checkpoint_save_ms",
                "Checkpoint save call wall-clock (per-shard writes + "
                "digest manifest).",
                buckets=log_buckets(10.0, 1e6)),
            "ckpt_restore_ms": REGISTRY.histogram(
                "trainer_checkpoint_restore_ms",
                "Checkpoint restore wall-clock.",
                buckets=log_buckets(10.0, 1e6)),
            "restarts": REGISTRY.counter(
                "trainer_restarts_total",
                "Bounded in-process fit restarts (restore + "
                "fast-forward) taken after step failures."),
        }
    return _TRAINER_METRICS


def make_loss(name: str) -> Callable:
    import jax.numpy as jnp
    import optax

    if name == "softmax_cross_entropy":
        def loss(logits, labels, weights):
            l = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels.astype(jnp.int32))
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    elif name == "sigmoid_cross_entropy":
        def loss(logits, labels, weights):
            l = optax.sigmoid_binary_cross_entropy(logits[..., 0], labels)
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    elif name == "squared_error":
        def loss(logits, labels, weights):
            l = jnp.square(logits[..., 0] - labels)
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    else:
        raise ValueError(f"unknown loss {name!r}; have {LOSSES}")
    return loss


def make_optimizer(name: str, learning_rate: float, momentum: float = 0.9,
                   weight_decay: float = 1e-4, clip_norm: float = 0.0):
    import optax
    if name == "sgd":
        tx = optax.sgd(learning_rate)
    elif name == "momentum":
        tx = optax.sgd(learning_rate, momentum=momentum)
    elif name == "adam":
        tx = optax.adam(learning_rate)
    elif name == "adamw":
        tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {name!r}; have {OPTIMIZERS}")
    if clip_norm and clip_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
    return tx


class NNLearner(Estimator, HasLabelCol, HasFeaturesCol):
    """Train an NNFunction on a labeled frame; returns an NNModel."""

    features_col = Param("features", "input column (vectors or images)", ptype=str)
    label_col = Param("label", "label column", ptype=str)
    weight_col = Param(None, "optional per-row weight column", ptype=str)
    arch = Param(None, "architecture config dict (builder + kwargs)", ptype=dict)
    model = Param(None, "optional warm-start NNFunction", complex=True)
    loss = Param("softmax_cross_entropy", "training loss",
                 validator=in_set(*LOSSES))
    optimizer = Param("momentum", "optimizer", validator=in_set(*OPTIMIZERS))
    learning_rate = Param(0.1, "peak learning rate", ptype=float)
    momentum = Param(0.9, "sgd momentum", ptype=float)
    weight_decay = Param(1e-4, "adamw weight decay", ptype=float)
    clip_norm = Param(0.0, "global-norm gradient clipping (0 = off); "
                      "guards deep-net fits against divergence at "
                      "aggressive peak learning rates", ptype=float)
    epochs = Param(10, "passes over the data", ptype=int)
    batch_size = Param(256, "global batch size", ptype=int)
    warmup_steps = Param(0, "linear LR warmup steps", ptype=int)
    cosine_decay = Param(True, "cosine-decay LR to 0 over training", ptype=bool)
    seed = Param(0, "init/shuffle seed", ptype=int)
    mesh_shape = Param(None, "mesh axes dict, e.g. {'data': -1}; a "
                       "'model' axis > 1 turns on tensor parallelism "
                       "(params + optimizer state sharded per "
                       "parallel/dist rules, XLA inserts the "
                       "collectives)", ptype=dict)
    checkpoint_dir = Param(None, "sharded step-checkpoint directory "
                           "(io/checkpoint native store)", ptype=str)
    checkpoint_every = Param(0, "steps between checkpoints (0 = off)", ptype=int)
    push_gateway_url = Param(None, "optional metrics remote-write URL "
                             "(Prometheus Pushgateway job path or any "
                             "endpoint accepting the text exposition): "
                             "a MetricsPusher POSTs the process "
                             "registry there on an interval during "
                             "fit, with a final flush when the fit "
                             "ends — a batch fit's telemetry reaches a "
                             "LIVE Prometheus even though the job "
                             "exits between scrapes (checkpoint-side "
                             ".prom snapshots remain the on-disk "
                             "fallback)", ptype=str)
    push_interval_s = Param(30.0, "seconds between remote-write pushes",
                            ptype=float, validator=in_range(lo=1.0))
    max_restarts = Param(2, "bounded in-process auto-restarts: when a "
                         "train step fails and checkpointing is "
                         "configured, restore the latest step "
                         "checkpoint and resume the SAME shuffle "
                         "stream (deterministic fast-forward); after "
                         "this many restores the error propagates — a "
                         "persistent fault must fail the fit, not loop "
                         "it", ptype=int)
    fault_injector = Param(None, "chaos-test hook: callable(global_step)"
                           " invoked before each host-loop step; "
                           "exceptions it raises exercise the bounded-"
                           "restart path (see testing.faults.FaultPlan."
                           "step_fault)", complex=True)
    log_every = Param(50, "steps between loss logs (0 = off)", ptype=int)
    device_resident = Param(False, "upload the dataset to the device ONCE "
                            "and run each epoch as one scanned device "
                            "program (batches gathered on device from an "
                            "uploaded permutation): one dispatch + one "
                            "loss fetch per epoch instead of a transfer "
                            "per step — the fit shape for high-latency "
                            "host<->device links (integer image data "
                            "stays integer on the wire and is "
                            "normalized on device). Single-data-shard "
                            "fits only; falls back otherwise", ptype=bool)
    augment = Param("none", "on-device per-batch augmentation: flip_crop "
                    "= random horizontal flip + random 4px translate "
                    "(the standard CIFAR recipe), applied inside the "
                    "jitted step", validator=in_set("none", "flip_crop"))

    # -- jitted step construction ------------------------------------------

    def build_train_step(self, module, tx, loss_fn):
        """(params, opt_state, batch) -> (params, opt_state, loss), jittable."""
        import jax

        def step(params, opt_state, x, y, w):
            def objective(p):
                logits = module.apply(p, x, train=True)
                return loss_fn(logits, y, w)

            loss, grads = jax.value_and_grad(objective)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return step

    @staticmethod
    def _augment_flip_crop(key, xb):
        """Random horizontal flip + random 4px translate, on device."""
        import jax
        import jax.numpy as jnp
        b, hgt, wid = xb.shape[0], xb.shape[1], xb.shape[2]
        k1, k2 = jax.random.split(key)
        flip = jax.random.bernoulli(k1, 0.5, (b,))
        xb = jnp.where(flip[:, None, None, None], xb[:, :, ::-1, :], xb)
        pad = 4
        padded = jnp.pad(xb, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                         mode="reflect")
        offs = jax.random.randint(k2, (b, 2), 0, 2 * pad + 1)

        def crop(img, o):
            return jax.lax.dynamic_slice(
                img, (o[0], o[1], 0), (hgt, wid, img.shape[-1]))

        return jax.vmap(crop)(padded, offs)

    def _fit_device_resident(self, x, y, w, fn, module, bs, tx, loss_fn):
        """Whole-epoch scanned training with a device-resident dataset.

        The per-step host loop below pays one host->device batch
        transfer and one dispatch per step — hundreds of link
        round-trips per epoch on a tunneled chip. Here the dataset
        (kept uint8 if it arrived uint8: 4x fewer link bytes than f32)
        is uploaded once, each epoch's shuffled batch indices are one
        small int32 upload, and ``lax.scan`` gathers + steps entirely
        on device: one dispatch and one loss fetch per epoch. The same
        shape as the fused GBDT fit (`gbdt/tree.py::boost_loop_device`).
        """
        import jax
        import jax.numpy as jnp

        # ONLY uint8 is treated as image bytes (x/255 + a uint8-tagged
        # scorer); other integer dtypes are plain numerics cast to f32 —
        # scaling counts by 1/255 and round-tripping them through uint8
        # at scoring time would silently corrupt values > 255
        is_int = x.dtype == np.uint8
        scale = np.float32(1.0 / 255.0) if is_int else np.float32(1.0)
        # datasets smaller than the batch keep working (the host loop
        # pads ragged batches; here the batch shrinks to the data)
        bs = min(bs, len(x))
        steps_per_epoch = max(len(x) // bs, 1)
        x_dev = jnp.asarray(x)
        y_dev = jnp.asarray(y)
        w_dev = jnp.asarray(w)
        step_fn = self.build_train_step(module, tx, loss_fn)
        aug = self.augment

        def epoch_fn(params, opt_state, key, perm):
            def body(carry, idx):
                p, o, k = carry
                k, k_aug = jax.random.split(k)
                xb = x_dev[idx].astype(jnp.float32) * scale
                if aug == "flip_crop":
                    xb = self._augment_flip_crop(k_aug, xb)
                p, o, loss = step_fn(p, o, xb, y_dev[idx], w_dev[idx])
                return (p, o, k), loss

            (params, opt_state, _), losses = jax.lax.scan(
                body, (params, opt_state, key), perm)
            return params, opt_state, losses

        epoch_jit = jax.jit(epoch_fn, donate_argnums=(0, 1))

        params = jax.device_put(fn.params)
        opt_state = tx.init(params)
        rng = np.random.default_rng(self.seed)
        n_use = steps_per_epoch * bs
        from mmlspark_tpu.core.tracing import ambient_tracer
        TRACER = ambient_tracer()
        for epoch in range(self.epochs):
            perm = rng.permutation(len(x))[:n_use].astype(np.int32) \
                .reshape(steps_per_epoch, bs)
            key = jax.random.PRNGKey(self.seed * 100003 + epoch)
            # the scanned fit's unit of work is the EPOCH (one dispatch
            # + one loss fetch), so that is its span granularity
            with TRACER.span("train_epoch", route="trainer",
                             epoch=epoch + 1,
                             steps=int(steps_per_epoch)):
                params, opt_state, losses = epoch_jit(
                    params, opt_state, key, jnp.asarray(perm))
            if self.log_every:
                print(f"[NNLearner] epoch {epoch + 1}/{self.epochs} "
                      f"mean loss {float(jnp.mean(losses)):.5f}")

        trained = NNFunction(arch=dict(fn.arch),
                             params=jax.device_get(params))
        # an integer-trained model's scorer must keep the same input
        # convention (uint8 in, /255 on device) or every consumer would
        # silently feed 0-255 floats into a net trained on [0, 1]
        extra = {"input_dtype": "uint8"} if is_int else {}
        return NNModel(model=trained, input_col=self.features_col,
                       output_col="scores", **extra)

    def _schedule(self, steps_per_epoch: int):
        import optax
        warmup = max(self.warmup_steps, 1)
        total = max(self.epochs * steps_per_epoch, warmup + 1)
        if self.cosine_decay:
            return optax.warmup_cosine_decay_schedule(
                0.0, self.learning_rate, warmup, total)
        if self.warmup_steps:
            return optax.linear_schedule(0.0, self.learning_rate,
                                         self.warmup_steps)
        return self.learning_rate

    # -- fit ----------------------------------------------------------------

    def fit(self, df: DataFrame) -> NNModel:
        if not self.push_gateway_url:
            return self._fit(df)
        # remote-write rides the whole fit: periodic pushes while the
        # host loop runs, one final flush in the finally (success OR
        # failure — a crashed fit's last counters are exactly the
        # telemetry worth having). Step/egress spans carry trace
        # context on any HTTP the fit fans out (io/http injects the
        # ambient train_step span), so pushed exemplars and captured
        # step traces stay correlated.
        from mmlspark_tpu.core.telemetry import MetricsPusher
        with MetricsPusher(self.push_gateway_url,
                           interval_s=self.push_interval_s):
            return self._fit(df)

    def _fit(self, df: DataFrame) -> NNModel:
        import jax
        import optax

        from mmlspark_tpu.models.nn import _stack_column
        # _stack_column preserves source dtype; training computes in
        # f32, but a device-resident fit keeps integer image data
        # integer ON THE LINK and normalizes on device
        x = _stack_column(df[self.features_col])
        # uint8 survives for BOTH paths (each normalizes /255 and tags
        # the scorer identically — a perf flag must never change the
        # learned function); every other dtype trains as f32
        if x.dtype != np.uint8:
            x = x.astype(np.float32, copy=False)
        y = np.asarray(df[self.label_col])
        w = (np.asarray(df[self.weight_col], dtype=np.float32)
             if self.weight_col else np.ones(len(y), dtype=np.float32))

        fn = self.model or NNFunction.init(self.arch, x.shape[1:],
                                           seed=self.seed)
        module = fn.module()

        from mmlspark_tpu.parallel.topology import in_single_device_scope
        if in_single_device_scope():
            # pinned-trial context (TuneHyperparameters trial_devices):
            # train on the thread's default device only
            dev = jax.config.jax_default_device or jax.local_devices()[0]
            mesh = build_mesh(MeshSpec.from_dict({"data": 1}),
                              devices=[dev])
        else:
            mesh = build_mesh(MeshSpec.from_dict(self.mesh_shape)
                              if self.mesh_shape else None)
        n_data = mesh.shape.get("data", 1)
        bs = max(self.batch_size - self.batch_size % n_data, n_data)
        steps_per_epoch = max(len(x) // bs, 1)

        tx = make_optimizer(self.optimizer, self._schedule(steps_per_epoch),
                            self.momentum, self.weight_decay,
                            self.clip_norm)
        loss_fn = make_loss(self.loss)
        if self.device_resident and n_data == 1 \
                and self._checkpoint_manager() is None:
            return self._fit_device_resident(x, y, w, fn, module, bs,
                                             tx, loss_fn)
        if self.augment != "none":
            import warnings
            warnings.warn(
                "augment is applied by the device-resident scanned fit "
                "only; this fit takes the per-step host loop "
                f"(device_resident={self.device_resident}, data shards="
                f"{n_data}, checkpointing="
                f"{self.checkpoint_dir is not None}) and trains WITHOUT "
                "augmentation", stacklevel=2)
        was_int = x.dtype == np.uint8        # image bytes only, as above
        if was_int:
            x = x.astype(np.float32) / 255.0   # host fallback normalizes
        step = jax.jit(self.build_train_step(module, tx, loss_fn),
                       donate_argnums=(0, 1))

        # state placement: replicated on a pure-data mesh (byte-for-byte
        # the pre-TP behavior — every spec degenerates to P() when no
        # model axis exists), model-sharded per the dist rule otherwise;
        # optimizer moments land with their param's layout because the
        # rule is shape-driven. The jitted step donates both trees, so
        # the sharded update happens in place in device memory.
        from mmlspark_tpu.parallel import dist as _dist
        repl = _dist.state_shardings(fn.params, mesh)
        params = jax.device_put(fn.params, repl)
        opt_state = tx.init(params)
        opt_repl = _dist.state_shardings(opt_state, mesh)
        opt_state = jax.device_put(opt_state, opt_repl)

        start_step = 0
        mngr = self._checkpoint_manager()
        template = None
        if mngr is not None:
            # host-side structure template, captured BEFORE any step
            # runs: the jitted step donates its params/opt_state
            # buffers, so after a mid-step fault the live buffers may
            # already be invalidated — restores must not depend on them
            template = {"params": jax.device_get(params),
                        "opt_state": jax.device_get(opt_state)}
        if mngr is not None and mngr.latest_step() is not None:
            raw_params, raw_opt, start_step = self._restore(mngr, template)
            params = jax.device_put(raw_params, repl)
            opt_state = jax.device_put(raw_opt, opt_repl)

        # -- fault-tolerant fit: a step failure (preempted chip, injected
        # chaos fault, failed checkpoint write) restores the latest
        # checkpoint and re-enters the SAME deterministic shuffle stream
        # (the fast-forward below), bounded by max_restarts so a
        # persistent fault still fails the fit
        restarts = 0
        while True:
            try:
                params, opt_state = self._host_loop(
                    x, y, w, step, mesh, params, opt_state, start_step,
                    steps_per_epoch, bs, n_data, mngr)
                break
            except Exception as e:  # noqa: BLE001 — classified below
                if isinstance(e, NotImplementedError):
                    raise   # a permanent capability gap, not a fault
                if mngr is None or restarts >= self.max_restarts:
                    raise
                restarts += 1
                _metrics()["restarts"].inc()
                latest = mngr.latest_step()
                print(f"[NNLearner] step failed ({type(e).__name__}: {e});"
                      f" restoring "
                      f"{'step ' + str(latest) if latest is not None else 'init'}"
                      f" (restart {restarts}/{self.max_restarts})")
                if latest is None:
                    params = jax.device_put(fn.params, repl)
                    opt_state = jax.device_put(tx.init(params), opt_repl)
                    start_step = 0
                else:
                    raw_params, raw_opt, start_step = \
                        self._restore(mngr, template)
                    params = jax.device_put(raw_params, repl)
                    opt_state = jax.device_put(raw_opt, opt_repl)

        trained = NNFunction(arch=dict(fn.arch), params=jax.device_get(params))
        # keep the training-time input convention (see _fit_device_resident)
        extra = {"input_dtype": "uint8"} if was_int else {}
        return NNModel(model=trained, input_col=self.features_col,
                       output_col="scores", **extra)

    def _host_loop(self, x, y, w, step, mesh, params, opt_state,
                   start_step, steps_per_epoch, bs, n_data, mngr):
        """One attempt at the per-step host loop, resumable at
        ``start_step``: the shuffle stream is regenerated from the seed
        and already-done steps are skipped, so every attempt sees the
        identical batch sequence (restart N reaches the same params an
        uninterrupted run does)."""
        import jax
        from mmlspark_tpu.parallel import dist as _dist

        from mmlspark_tpu.core.tracing import ambient_tracer
        TRACER = ambient_tracer()

        rng = np.random.default_rng(self.seed)
        metrics = _metrics()
        m_step, m_eps = metrics["step_ms"], metrics["examples_per_sec"]
        global_step = 0
        # ragged-tail staging reuse: the last batch of every epoch pads
        # to the data multiple through ONE buffer instead of a fresh
        # allocation per step (dist.put_batch pad_cache contract)
        pad_cache: dict = {}
        # per-attempt dispatch-shape memory: a batch shape this attempt
        # has not dispatched yet forces a jit retrace, and the step's
        # span marks it (recompile=True) so a captured slow step says
        # WHY it was slow (the ragged tail batch is the usual culprit)
        shapes_seen: set = set()
        # bound the number of dispatched-but-unfinished steps: an
        # unthrottled loop queues every step at once, and XLA:CPU's
        # cross-device collective rendezvous can deadlock when executions
        # from many run_ids oversubscribe the shared thread pool (the
        # virtual 8-device test mesh hits this). A window of 2 keeps
        # host/device pipelining on real chips while serializing enough.
        from collections import deque
        inflight: deque = deque()
        for epoch in range(self.epochs):
            order = rng.permutation(len(x))
            for s in range(steps_per_epoch):
                global_step += 1
                if global_step <= start_step:
                    continue  # fast-forward after resume (same shuffle stream)
                # one root span per step (route "trainer"): a chaos
                # fault raised inside finishes it with status=error, so
                # failed steps are tail-captured with their timeline;
                # the step_ms observe below runs inside the span, so
                # the histogram's exemplar links a slow bucket straight
                # to the captured step trace
                with TRACER.span("train_step", route="trainer",
                                 step=global_step,
                                 epoch=epoch + 1) as sp:
                    if self.fault_injector is not None:
                        self.fault_injector(global_step)
                    t_step = time.perf_counter()
                    idx = order[s * bs:(s + 1) * bs]
                    # ragged tail: pad to the data-axis multiple, zero
                    # the pad rows' weights so they contribute nothing
                    # to the loss
                    xp, n_real = pad_to_multiple(x[idx], n_data)
                    yp, _ = pad_to_multiple(y[idx], n_data)
                    wp, _ = pad_to_multiple(w[idx], n_data)
                    if n_real < len(wp):
                        wp = wp.copy()
                        wp[n_real:] = 0.0
                    recompile = xp.shape not in shapes_seen
                    if recompile:
                        shapes_seen.add(xp.shape)
                    t_disp = TRACER.clock.now()
                    # data-sharded global placement. Multi-process: the
                    # shuffle stream is seed-identical on every host, so
                    # each host contributes ONLY its row slice of the
                    # padded global batch and parallel/dist assembles —
                    # feeding the full batch would duplicate every row
                    # n_proc times and silently change the gradient
                    if jax.process_count() > 1:
                        plo, phi = _dist.process_local_rows(len(xp), mesh)
                        xp, yp, wp = xp[plo:phi], yp[plo:phi], wp[plo:phi]
                    placed, _ = _dist.put_batch(
                        {"x": xp, "y": yp, "w": wp}, mesh,
                        pad_cache=pad_cache)
                    xb, yb, wb = placed["x"], placed["y"], placed["w"]
                    params, opt_state, loss = step(params, opt_state,
                                                   xb, yb, wb)
                    inflight.append(loss)
                    if len(inflight) > 2:
                        inflight.popleft().block_until_ready()
                    # dispatch is async: this child is transfer +
                    # enqueue time, plus the periodic device block when
                    # the in-flight window fills (and the whole trace/
                    # compile, on a recompile=True step)
                    TRACER.add("step_dispatch", t_disp,
                               TRACER.clock.now(), parent=sp,
                               recompile=recompile, batch=int(len(xp)))
                    dt = time.perf_counter() - t_step
                    m_step.observe(dt * 1000.0)
                    if dt > 0:
                        m_eps.observe(n_real / dt)
                    if self.log_every and global_step % self.log_every == 0:
                        print(f"[NNLearner] step {global_step} "
                              f"epoch {epoch + 1}/{self.epochs} "
                              f"loss {float(loss):.5f}")
                    if (mngr is not None and self.checkpoint_every
                            and global_step % self.checkpoint_every == 0):
                        self._checkpoint(mngr, global_step, params,
                                         opt_state)
        if mngr is not None:
            self._checkpoint(mngr, global_step, params, opt_state)
            mngr.wait_until_finished()
        return params, opt_state

    # -- sharded step checkpointing ----------------------------------------

    def _checkpoint_manager(self):
        if not self.checkpoint_dir:
            return None
        # multi-process runtimes save cooperatively into ONE directory
        # (io/checkpoint.save_sharded: per-slice shard ownership +
        # barriers, manifest by process 0) — checkpoint_dir must sit
        # on a filesystem every host shares, the standard pod setup
        from mmlspark_tpu.io import checkpoint as _ckpt
        return _ckpt.manager(self.checkpoint_dir)

    def _checkpoint(self, mngr, step_num: int, params, opt_state) -> None:
        from mmlspark_tpu.core.tracing import ambient_tracer
        TRACER = ambient_tracer()
        with TRACER.span("checkpoint_save", step=step_num), \
                _metrics()["ckpt_save_ms"].time():
            # the live trees are written shard-by-shard (replicated
            # leaves once, model-sharded leaves per slice) — no host
            # gather; the digest manifest lands last
            mngr.save(step_num,
                      {"params": params, "opt_state": opt_state})
        # a scrape rides every checkpoint: batch fits usually exit (or
        # are preempted) before any Prometheus scrape, so the registry
        # state lands next to the step it describes — under telemetry/
        # (NOT the checkpoint root: the manager owns that namespace's
        # step listing). Best-effort: telemetry must never fail a save.
        try:
            from mmlspark_tpu.core.telemetry import snapshot_registries
            from mmlspark_tpu.io import fs as _fs
            snapshot_registries(_fs.join(self.checkpoint_dir, "telemetry"),
                                tag=f"step{step_num:08d}", keep=8)
        except Exception:  # noqa: BLE001
            from mmlspark_tpu.core.logs import get_logger
            get_logger("trainer").warning(
                "checkpoint metrics snapshot failed", exc_info=True)

    def _restore(self, mngr, template):
        """Restore the latest step against a host-side (params,
        opt_state) structure template, so optax NamedTuple states
        round-trip intact. The template must predate the first step:
        the donated live buffers are not safe to read after a fault.
        Host arrays come back; the caller re-places them with the
        current mesh's shardings — which may differ from the saving
        run's (topology-change resume)."""
        from mmlspark_tpu.core.tracing import ambient_tracer
        TRACER = ambient_tracer()
        latest = mngr.latest_step()
        with TRACER.span("checkpoint_restore", step=latest), \
                _metrics()["ckpt_restore_ms"].time():
            restored = mngr.restore(latest, template)
        print(f"[NNLearner] resumed from step {latest}")
        return restored["params"], restored["opt_state"], latest

    # -- incremental training from a stream ---------------------------------

    def fit_stream(self, source, export_dir: Optional[str] = None,
                   export_every_batches: int = 4,
                   export_prefix: str = "r",
                   steps_per_batch: int = 1,
                   checkpoint_every_batches: int = 1,
                   transform=None,
                   **query_kwargs) -> "StreamingFit":
        """Train incrementally from a micro-batch stream.

        ``source`` is either an engine source (``plan``/``read``/
        ``ack`` — e.g. a :class:`~mmlspark_tpu.streaming.traffic.
        TrafficLogSource` over served-traffic capture segments) from
        which a :class:`~mmlspark_tpu.streaming.engine.StreamingQuery`
        is built (``query_kwargs`` forwarded — ``checkpoint_dir`` for
        the WAL, watermarks, backpressure knobs), or an already-built
        ``StreamingQuery`` whose sink slot is free — ``fit_stream``
        installs itself as the sink either way.

        Semantics: every micro-batch becomes ``steps_per_batch``
        gradient steps on the SAME mesh-sharded, donated jitted step
        ``fit`` uses (rows padded to the data-axis multiple on a
        power-of-two ladder, pad rows zero-weighted — the compiled
        shape set stays bounded). With ``checkpoint_dir`` (the Param)
        set, the fit WARM-STARTS from the latest digest-manifested
        train-state checkpoint and saves one every
        ``checkpoint_every_batches`` batches (default 1: EVERY
        trained batch), recording the high-water stream batch id
        inside it — a post-crash replayed batch id at or below that
        mark is SKIPPED, which is what makes this sink idempotent and
        the end-to-end loop exactly-once. Raising
        ``checkpoint_every_batches`` above 1 trades durability for
        save cost: batches the engine committed AFTER the last
        train-state checkpoint warm-start as if untrained after a
        crash (at-most-once inside that window) — acceptable for
        training (a lost gradient step is not a lost reply), but the
        default keeps the strict contract. With ``export_dir`` set, a
        servable ``NNModel`` stage checkpoint is exported every
        ``export_every_batches`` batches on its own cadence (manifest
        written last, so every export is flip-eligible the moment it
        appears — what a
        :class:`~mmlspark_tpu.streaming.loop.RetrainLoop` watches).

        Streaming fits use a constant learning rate (no fixed horizon
        to decay over); ``cosine_decay``/``warmup_steps`` are ignored.
        Returns a :class:`StreamingFit` handle (drive the query
        synchronously via ``handle.query.process_available()`` or
        threaded via ``handle.query.start()``).
        """
        from mmlspark_tpu.streaming.engine import StreamingQuery
        sink = _StreamTrainerSink(self, export_dir=export_dir,
                                  export_every=export_every_batches,
                                  export_prefix=export_prefix,
                                  steps_per_batch=steps_per_batch,
                                  checkpoint_every=checkpoint_every_batches)
        if isinstance(source, StreamingQuery):
            if source.sink is not None:
                raise ValueError(
                    "fit_stream needs the query's sink slot (build the "
                    "StreamingQuery with sink=None)")
            if query_kwargs or transform is not None:
                raise ValueError(
                    "pass transform/query knobs when fit_stream builds "
                    "the query, not alongside a pre-built one")
            query = source
            query.sink = sink
        else:
            query_kwargs.setdefault("name", "fit_stream")
            query = StreamingQuery(source, sink=sink,
                                   transform=transform, **query_kwargs)
        return StreamingFit(query, sink)


def _as_label(v) -> float:
    """A usable numeric label or NaN (filtered): captured traffic rows
    carry JSON values, so None holes / strings / lists are expected."""
    try:
        if isinstance(v, (list, tuple, np.ndarray)):
            return float("nan")
        return float(v)
    except (TypeError, ValueError):
        return float("nan")


class _StreamTrainerSink:
    """The ``fit_stream`` sink: micro-batches -> sharded train steps.

    Idempotent by batch id: the train-state checkpoint records the
    high-water stream batch id it covers, so a replayed batch (the
    engine re-runs planned-but-uncommitted batches after a crash) at or
    below the restored mark is skipped — replay beats re-dispatch, and
    a crash anywhere in the write/commit window never trains a batch
    twice past a checkpoint. Lazily initialized on the first frame
    (shapes come from the stream).
    """

    def __init__(self, learner: NNLearner, export_dir: Optional[str],
                 export_every: int, export_prefix: str,
                 steps_per_batch: int, checkpoint_every: int = 1):
        self.learner = learner
        self.export_dir = (os.path.abspath(export_dir)
                           if export_dir else None)
        self.export_every = max(int(export_every), 1)
        self.export_prefix = str(export_prefix)
        self.steps_per_batch = max(int(steps_per_batch), 1)
        self.checkpoint_every = max(int(checkpoint_every), 1)
        self._ready = False
        self._was_int = False
        self.last_trained_batch = 0
        self.global_step = 0
        self.n_batches_trained = 0
        self.n_rows_trained = 0
        self.n_replays_skipped = 0
        self.n_rows_unlabeled = 0
        self.n_batches_unusable = 0
        self.n_exports = 0
        self.exports: "list[str]" = []
        self.last_loss: Optional[float] = None

    # -- lazy setup ----------------------------------------------------------

    def _setup(self, x: np.ndarray) -> None:
        import jax
        from mmlspark_tpu.parallel import dist as _dist

        learner = self.learner
        self._was_int = x.dtype == np.uint8
        shape = ((x.shape[1:]) if x.ndim > 1 else ())
        fn = learner.model or NNFunction.init(
            learner.arch, shape, seed=learner.seed)
        self._arch = dict(fn.arch)
        module = fn.module()
        self._mesh = build_mesh(MeshSpec.from_dict(learner.mesh_shape)
                                if learner.mesh_shape else None)
        self._n_data = self._mesh.shape.get("data", 1)
        # a stream has no fixed horizon: constant learning rate (the
        # schedule params cosine_decay/warmup_steps are batch-fit only)
        tx = make_optimizer(learner.optimizer, learner.learning_rate,
                            learner.momentum, learner.weight_decay,
                            learner.clip_norm)
        self._step = jax.jit(
            learner.build_train_step(module, tx, make_loss(learner.loss)),
            donate_argnums=(0, 1))
        repl = _dist.state_shardings(fn.params, self._mesh)
        params = jax.device_put(fn.params, repl)
        opt_state = tx.init(params)
        opt_repl = _dist.state_shardings(opt_state, self._mesh)
        opt_state = jax.device_put(opt_state, opt_repl)
        self._repl, self._opt_repl = repl, opt_repl
        self._dist = _dist
        self._pad_cache: dict = {}
        self._mngr = learner._checkpoint_manager()
        if self._mngr is not None:
            # host-side template BEFORE any step: the donated buffers
            # are not restore-safe afterwards (same rule as fit)
            template = {"params": jax.device_get(params),
                        "opt_state": jax.device_get(opt_state)}
            self._template = template
            latest = self._mngr.latest_step()
            if latest is not None:
                restored = self._mngr.restore(latest, template)
                params = jax.device_put(restored["params"], repl)
                opt_state = jax.device_put(restored["opt_state"],
                                           opt_repl)
                self.global_step = int(latest)
                from mmlspark_tpu.io.checkpoint import read_index
                extra = read_index(
                    self._mngr._step_dir(latest)).get("extra", {})
                self.last_trained_batch = int(
                    extra.get("stream_batch_id", 0))
                self.n_exports = int(extra.get("n_exports", 0))
                print(f"[NNLearner] fit_stream warm-started from step "
                      f"{latest} (stream batch "
                      f"{self.last_trained_batch})")
        if self.export_dir:
            os.makedirs(self.export_dir, exist_ok=True)
            # continue the export sequence past anything already there
            # (a restarted loop must never reuse a pushed version name)
            for name in os.listdir(self.export_dir):
                if name.startswith(self.export_prefix):
                    try:
                        self.n_exports = max(
                            self.n_exports,
                            int(name[len(self.export_prefix):]))
                    except ValueError:
                        continue
        self._params, self._opt = params, opt_state

    # -- the sink ------------------------------------------------------------

    def process(self, batch_id: int, df: DataFrame) -> None:
        from mmlspark_tpu.models.nn import _stack_column
        from mmlspark_tpu.parallel.sharding import (
            pad_to_bucket, pad_to_multiple)

        learner = self.learner
        if df.num_rows == 0 or learner.features_col not in df:
            return
        # bad DATA must never kill the retrain loop: captured traffic
        # routinely mixes labeled (feedback) and unlabeled (plain
        # inference) rows, and a malformed payload is a data problem,
        # not a query-terminal fault. Rows without a usable numeric
        # label are dropped (counted); a batch with nothing trainable
        # is ignored — deterministically, so a replay skips it too.
        try:
            if learner.label_col in df:
                y_raw = df[learner.label_col]
                if y_raw.dtype == object:
                    y = np.array([_as_label(v) for v in y_raw],
                                 dtype=np.float32)
                else:
                    y = np.asarray(y_raw, dtype=np.float32)
                mask = np.isfinite(y)
            else:
                y = np.zeros(df.num_rows, dtype=np.float32)
                mask = np.zeros(df.num_rows, dtype=bool)
            n_bad = int(df.num_rows - mask.sum())
            if n_bad:
                self.n_rows_unlabeled += n_bad
            if not mask.any():
                return
            if n_bad:
                df = df.filter(mask)
                y = y[mask]
            x = _stack_column(df[learner.features_col])
            if not self._ready:
                self._setup(x)
                self._ready = True
            if self._was_int and x.dtype == np.uint8:
                x = x.astype(np.float32) / 255.0
            elif x.dtype != np.float32:
                x = np.asarray(x, dtype=np.float32)
            w = (np.asarray(df[learner.weight_col], dtype=np.float32)
                 if learner.weight_col and learner.weight_col in df
                 else np.ones(len(y), dtype=np.float32))
        except (KeyError, TypeError, ValueError) as e:
            # a data-shape problem (ragged features, non-numeric
            # payloads): skip the batch loudly, keep the stream alive
            self.n_batches_unusable += 1
            from mmlspark_tpu.core.logs import get_logger
            get_logger("trainer").warning(
                "fit_stream batch %d unusable (%s: %s); skipped",
                batch_id, type(e).__name__, e)
            return
        if batch_id <= self.last_trained_batch:
            # the idempotent-sink contract: this batch is already
            # inside the restored checkpoint's high-water mark
            self.n_replays_skipped += 1
            return
        # two-stage pad: power-of-two bucket (bounded compile set under
        # ragged stream batches), then the data-axis multiple; pad rows
        # carry zero weight so they contribute nothing to the loss
        cap = max(int(learner.batch_size), self._n_data)
        xp, n_real = pad_to_bucket(x, cap=cap)
        xp, _ = pad_to_multiple(xp, self._n_data)
        target = len(xp)
        yp = np.zeros(target, dtype=np.float32)
        yp[:n_real] = y[:n_real]
        wp = np.zeros(target, dtype=np.float32)
        wp[:n_real] = w[:n_real]
        metrics = _metrics()
        for _ in range(self.steps_per_batch):
            t0 = time.perf_counter()
            placed, _ = self._dist.put_batch(
                {"x": xp, "y": yp, "w": wp}, self._mesh,
                pad_cache=self._pad_cache)
            self._params, self._opt, loss = self._step(
                self._params, self._opt,
                placed["x"], placed["y"], placed["w"])
            self.global_step += 1
            dt = time.perf_counter() - t0
            metrics["step_ms"].observe(dt * 1000.0)
            if dt > 0:
                metrics["examples_per_sec"].observe(n_real / dt)
        self.last_loss = float(loss)
        self.last_trained_batch = int(batch_id)
        self.n_batches_trained += 1
        self.n_rows_trained += int(n_real)
        # two independent cadences: the train-state checkpoint is the
        # exactly-once high-water mark (default every batch — raising
        # the cadence opens an at-most-once window after a crash, see
        # fit_stream); the servable export is the rollout feed
        if self.n_batches_trained % self.checkpoint_every == 0:
            self._save_train_state()
        if self.export_dir \
                and self.n_batches_trained % self.export_every == 0:
            self._export()

    # -- checkpoint + servable export ----------------------------------------

    def _save_train_state(self) -> None:
        """Save the train state; the idempotence high-water mark
        (``stream_batch_id``) rides in ``extra``."""
        if self._mngr is None:
            return
        with _metrics()["ckpt_save_ms"].time():
            self._mngr.save(
                self.global_step,
                {"params": self._params, "opt_state": self._opt},
                extra={"stream_batch_id": self.last_trained_batch,
                       "n_exports": self.n_exports})

    def _export(self) -> Optional[str]:
        """Export a servable NNModel stage checkpoint whose digest
        manifest lands LAST — flip-eligible for the rollout plane the
        moment the directory is complete."""
        if not self.export_dir:
            return None
        self.n_exports += 1
        name = f"{self.export_prefix}{self.n_exports:06d}"
        path = os.path.join(self.export_dir, name)
        self.model().save(path)
        self.exports.append(path)
        return path

    def checkpoint_and_export(self) -> Optional[str]:
        """Off-cadence save + export (drain/shutdown; ``export_now``)."""
        if not self._ready:
            return None
        path = self._export()
        self._save_train_state()
        return path

    def model(self) -> NNModel:
        """A servable snapshot of the current streamed-trained model."""
        if not self._ready:
            raise RuntimeError("fit_stream has not seen a batch yet")
        import jax
        fn = NNFunction(arch=dict(self._arch),
                        params=jax.device_get(self._params))
        extra = {"input_dtype": "uint8"} if self._was_int else {}
        return NNModel(model=fn, input_col=self.learner.features_col,
                       output_col="scores", **extra)

    def status(self) -> Dict[str, Any]:
        return {"ready": self._ready,
                "global_step": self.global_step,
                "last_trained_batch": self.last_trained_batch,
                "n_batches_trained": self.n_batches_trained,
                "n_rows_trained": self.n_rows_trained,
                "n_replays_skipped": self.n_replays_skipped,
                "n_rows_unlabeled": self.n_rows_unlabeled,
                "n_batches_unusable": self.n_batches_unusable,
                "n_exports": self.n_exports,
                "exports": list(self.exports),
                "last_loss": self.last_loss}


class StreamingFit:
    """Handle over a streaming fit: the query (drive/stop it here) plus
    the trainer sink's counters, snapshots, and exports."""

    def __init__(self, query, sink: _StreamTrainerSink):
        self.query = query
        self._sink = sink

    @property
    def exports(self) -> "list[str]":
        return list(self._sink.exports)

    def model(self) -> NNModel:
        return self._sink.model()

    def export_now(self) -> Optional[str]:
        """Checkpoint + export outside the cadence (drain/shutdown)."""
        return self._sink.checkpoint_and_export()

    def status(self) -> Dict[str, Any]:
        return {"trainer": self._sink.status(),
                "query": self.query.status()}

    def stop(self) -> None:
        self.query.stop()

    def __enter__(self) -> "StreamingFit":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
