"""NNLearner: in-process data-parallel deep-net training on the mesh.

Capability parity with `src/cntk-train` (`CNTKLearner.scala:85-190`): an
Estimator that takes a labeled frame, trains a network with configurable
loss/optimizer/schedule (the role BrainScript configs play), and returns
an ``NNModel`` for scoring. The reference's entire data-export ->
ssh/scp -> `mpirun cntk` -> copy-model-back chain
(`CommandBuilders.scala:149-266`) collapses to a jitted train step with
sharding-induced ICI allreduce — zero processes, zero sockets, zero MPI.

Distribution: batches are sharded over the mesh's ``data`` axis, params
replicated (or sharded over ``model`` for TP); XLA inserts the gradient
allreduce. Step checkpointing via orbax covers the "resume" capability
(SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import math
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    Param, HasLabelCol, HasFeaturesCol, in_set, in_range,
)
from mmlspark_tpu.core.stage import Estimator
from mmlspark_tpu.models.function import NNFunction
from mmlspark_tpu.models.nn import NNModel
from mmlspark_tpu.parallel import (
    MeshSpec, build_mesh, batch_sharding, replicated_sharding, pad_to_multiple,
)

LOSSES = ("softmax_cross_entropy", "sigmoid_cross_entropy", "squared_error")
OPTIMIZERS = ("sgd", "momentum", "adam", "adamw")


def make_loss(name: str) -> Callable:
    import jax.numpy as jnp
    import optax

    if name == "softmax_cross_entropy":
        def loss(logits, labels, weights):
            l = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels.astype(jnp.int32))
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    elif name == "sigmoid_cross_entropy":
        def loss(logits, labels, weights):
            l = optax.sigmoid_binary_cross_entropy(logits[..., 0], labels)
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    elif name == "squared_error":
        def loss(logits, labels, weights):
            l = jnp.square(logits[..., 0] - labels)
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    else:
        raise ValueError(f"unknown loss {name!r}; have {LOSSES}")
    return loss


def make_optimizer(name: str, learning_rate: float, momentum: float = 0.9,
                   weight_decay: float = 1e-4):
    import optax
    if name == "sgd":
        return optax.sgd(learning_rate)
    if name == "momentum":
        return optax.sgd(learning_rate, momentum=momentum)
    if name == "adam":
        return optax.adam(learning_rate)
    if name == "adamw":
        return optax.adamw(learning_rate, weight_decay=weight_decay)
    raise ValueError(f"unknown optimizer {name!r}; have {OPTIMIZERS}")


class NNLearner(Estimator, HasLabelCol, HasFeaturesCol):
    """Train an NNFunction on a labeled frame; returns an NNModel."""

    features_col = Param("features", "input column (vectors or images)", ptype=str)
    label_col = Param("label", "label column", ptype=str)
    weight_col = Param(None, "optional per-row weight column", ptype=str)
    arch = Param(None, "architecture config dict (builder + kwargs)", ptype=dict)
    model = Param(None, "optional warm-start NNFunction", complex=True)
    loss = Param("softmax_cross_entropy", "training loss",
                 validator=in_set(*LOSSES))
    optimizer = Param("momentum", "optimizer", validator=in_set(*OPTIMIZERS))
    learning_rate = Param(0.1, "peak learning rate", ptype=float)
    momentum = Param(0.9, "sgd momentum", ptype=float)
    weight_decay = Param(1e-4, "adamw weight decay", ptype=float)
    epochs = Param(10, "passes over the data", ptype=int)
    batch_size = Param(256, "global batch size", ptype=int)
    warmup_steps = Param(0, "linear LR warmup steps", ptype=int)
    cosine_decay = Param(True, "cosine-decay LR to 0 over training", ptype=bool)
    seed = Param(0, "init/shuffle seed", ptype=int)
    mesh_shape = Param(None, "mesh axes dict, e.g. {'data': -1}", ptype=dict)
    checkpoint_dir = Param(None, "orbax step-checkpoint directory", ptype=str)
    checkpoint_every = Param(0, "steps between checkpoints (0 = off)", ptype=int)
    log_every = Param(50, "steps between loss logs (0 = off)", ptype=int)

    # -- jitted step construction ------------------------------------------

    def build_train_step(self, module, tx, loss_fn):
        """(params, opt_state, batch) -> (params, opt_state, loss), jittable."""
        import jax

        def step(params, opt_state, x, y, w):
            def objective(p):
                logits = module.apply(p, x, train=True)
                return loss_fn(logits, y, w)

            loss, grads = jax.value_and_grad(objective)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return step

    def _schedule(self, steps_per_epoch: int):
        import optax
        warmup = max(self.warmup_steps, 1)
        total = max(self.epochs * steps_per_epoch, warmup + 1)
        if self.cosine_decay:
            return optax.warmup_cosine_decay_schedule(
                0.0, self.learning_rate, warmup, total)
        if self.warmup_steps:
            return optax.linear_schedule(0.0, self.learning_rate,
                                         self.warmup_steps)
        return self.learning_rate

    # -- fit ----------------------------------------------------------------

    def fit(self, df: DataFrame) -> NNModel:
        import jax
        import optax

        from mmlspark_tpu.models.nn import _stack_column
        # _stack_column preserves source dtype (for integer-payload
        # scoring); training always computes in f32
        x = _stack_column(df[self.features_col]).astype(np.float32,
                                                        copy=False)
        y = np.asarray(df[self.label_col])
        w = (np.asarray(df[self.weight_col], dtype=np.float32)
             if self.weight_col else np.ones(len(y), dtype=np.float32))

        fn = self.model or NNFunction.init(self.arch, x.shape[1:],
                                           seed=self.seed)
        module = fn.module()

        from mmlspark_tpu.parallel.topology import in_single_device_scope
        if in_single_device_scope():
            # pinned-trial context (TuneHyperparameters trial_devices):
            # train on the thread's default device only
            dev = jax.config.jax_default_device or jax.local_devices()[0]
            mesh = build_mesh(MeshSpec.from_dict({"data": 1}),
                              devices=[dev])
        else:
            mesh = build_mesh(MeshSpec.from_dict(self.mesh_shape)
                              if self.mesh_shape else None)
        n_data = mesh.shape.get("data", 1)
        bs = max(self.batch_size - self.batch_size % n_data, n_data)
        steps_per_epoch = max(len(x) // bs, 1)

        tx = make_optimizer(self.optimizer, self._schedule(steps_per_epoch),
                            self.momentum, self.weight_decay)
        loss_fn = make_loss(self.loss)
        step = jax.jit(self.build_train_step(module, tx, loss_fn),
                       donate_argnums=(0, 1))

        repl = replicated_sharding(mesh)
        shard = batch_sharding(mesh)
        params = jax.device_put(fn.params, repl)
        opt_state = jax.device_put(tx.init(params), repl)

        start_step = 0
        mngr = self._checkpoint_manager()
        if mngr is not None and mngr.latest_step() is not None:
            raw_params, raw_opt, start_step = self._restore(mngr, params, opt_state)
            params = jax.device_put(raw_params, repl)
            opt_state = jax.device_put(raw_opt, repl)

        rng = np.random.default_rng(self.seed)
        global_step = 0
        # bound the number of dispatched-but-unfinished steps: an
        # unthrottled loop queues every step at once, and XLA:CPU's
        # cross-device collective rendezvous can deadlock when executions
        # from many run_ids oversubscribe the shared thread pool (the
        # virtual 8-device test mesh hits this). A window of 2 keeps
        # host/device pipelining on real chips while serializing enough.
        from collections import deque
        inflight: deque = deque()
        for epoch in range(self.epochs):
            order = rng.permutation(len(x))
            for s in range(steps_per_epoch):
                global_step += 1
                if global_step <= start_step:
                    continue  # fast-forward after resume (same shuffle stream)
                idx = order[s * bs:(s + 1) * bs]
                # ragged tail: pad to the data-axis multiple, zero the pad
                # rows' weights so they contribute nothing to the loss
                xp, n_real = pad_to_multiple(x[idx], n_data)
                yp, _ = pad_to_multiple(y[idx], n_data)
                wp, _ = pad_to_multiple(w[idx], n_data)
                if n_real < len(wp):
                    wp = wp.copy()
                    wp[n_real:] = 0.0
                xb = jax.device_put(xp, shard)
                yb = jax.device_put(yp, shard)
                wb = jax.device_put(wp, shard)
                params, opt_state, loss = step(params, opt_state, xb, yb, wb)
                inflight.append(loss)
                if len(inflight) > 2:
                    inflight.popleft().block_until_ready()
                if self.log_every and global_step % self.log_every == 0:
                    print(f"[NNLearner] step {global_step} "
                          f"epoch {epoch + 1}/{self.epochs} "
                          f"loss {float(loss):.5f}")
                if (mngr is not None and self.checkpoint_every
                        and global_step % self.checkpoint_every == 0):
                    self._checkpoint(mngr, global_step, params, opt_state)
        if mngr is not None:
            self._checkpoint(mngr, global_step, params, opt_state)
            mngr.wait_until_finished()

        trained = NNFunction(arch=dict(fn.arch), params=jax.device_get(params))
        return NNModel(model=trained, input_col=self.features_col,
                       output_col="scores")

    # -- orbax step checkpointing ------------------------------------------

    def _checkpoint_manager(self):
        if not self.checkpoint_dir:
            return None
        import orbax.checkpoint as ocp
        from mmlspark_tpu.io import fs as _fs
        # remote URLs (gs://...) pass through untouched — orbax's
        # tensorstore backend handles them natively on TPU VMs; only
        # local paths are absolutized (parity: the reference checkpoints
        # streaming state to HDFS, `HadoopUtils.scala`)
        path = (self.checkpoint_dir if _fs.is_remote(self.checkpoint_dir)
                else os.path.abspath(self.checkpoint_dir))
        return ocp.CheckpointManager(
            path,
            options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True))

    def _checkpoint(self, mngr, step_num: int, params, opt_state) -> None:
        import jax
        import orbax.checkpoint as ocp
        state = {"params": jax.device_get(params),
                 "opt_state": jax.device_get(opt_state)}
        mngr.save(step_num, args=ocp.args.StandardSave(state))

    def _restore(self, mngr, params, opt_state):
        """Restore against the live (params, opt_state) as structure template,
        so optax NamedTuple states round-trip intact."""
        import jax
        import orbax.checkpoint as ocp
        latest = mngr.latest_step()
        template = {"params": jax.device_get(params),
                    "opt_state": jax.device_get(opt_state)}
        restored = mngr.restore(latest, args=ocp.args.StandardRestore(template))
        print(f"[NNLearner] resumed from step {latest}")
        return restored["params"], restored["opt_state"], latest
