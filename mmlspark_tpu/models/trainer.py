"""NNLearner: in-process data-parallel deep-net training on the mesh.

Capability parity with `src/cntk-train` (`CNTKLearner.scala:85-190`): an
Estimator that takes a labeled frame, trains a network with configurable
loss/optimizer/schedule (the role BrainScript configs play), and returns
an ``NNModel`` for scoring. The reference's entire data-export ->
ssh/scp -> `mpirun cntk` -> copy-model-back chain
(`CommandBuilders.scala:149-266`) collapses to a jitted train step with
sharding-induced ICI allreduce — zero processes, zero sockets, zero MPI.

Distribution: batches are sharded over the mesh's ``data`` axis
(per-host input sharding on a multi-process runtime — each host feeds
only its rows); params and optimizer state are replicated, or sharded
over ``model`` for tensor parallelism when ``mesh_shape`` names a
``model`` axis (:mod:`mmlspark_tpu.parallel.dist` owns the sharding
rule; XLA/GSPMD inserts the gradient allreduce and the TP collectives
from the ``NamedSharding`` annotations, and the train state is donated
through every step so the optimizer update lands in place). Step
checkpointing uses the native sharded store
(:mod:`mmlspark_tpu.io.checkpoint`): each device writes its own
shards, and a resume may use a different topology than the save.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    Param, HasLabelCol, HasFeaturesCol, in_set, in_range,
)
from mmlspark_tpu.core.stage import Estimator
from mmlspark_tpu.models.function import NNFunction
from mmlspark_tpu.models.nn import NNModel
from mmlspark_tpu.parallel import MeshSpec, build_mesh, pad_to_multiple

LOSSES = ("softmax_cross_entropy", "sigmoid_cross_entropy", "squared_error")
OPTIMIZERS = ("sgd", "momentum", "adam", "adamw")

_TRAINER_METRICS = None


def _metrics():
    """Process-registry training telemetry, bound lazily so importing
    the trainer costs nothing."""
    global _TRAINER_METRICS
    if _TRAINER_METRICS is None:
        from mmlspark_tpu.core.telemetry import REGISTRY, log_buckets
        _TRAINER_METRICS = {
            "step_ms": REGISTRY.histogram(
                "trainer_step_ms",
                "Host-loop wall-clock per train step (dispatch is "
                "async: mostly host+transfer time, with periodic "
                "device blocks when the in-flight window fills)."),
            "examples_per_sec": REGISTRY.histogram(
                "trainer_examples_per_sec",
                "Real (unpadded) examples per second per host-loop "
                "step.", buckets=log_buckets(1.0, 1e7)),
            # wider ladder than the request-latency default: a
            # multi-GB save/restore routinely takes 30-120 s, and a
            # 10 s top edge would collapse every sample into +Inf
            "ckpt_save_ms": REGISTRY.histogram(
                "trainer_checkpoint_save_ms",
                "Checkpoint save call wall-clock (per-shard writes + "
                "digest manifest).",
                buckets=log_buckets(10.0, 1e6)),
            "ckpt_restore_ms": REGISTRY.histogram(
                "trainer_checkpoint_restore_ms",
                "Checkpoint restore wall-clock.",
                buckets=log_buckets(10.0, 1e6)),
            "restarts": REGISTRY.counter(
                "trainer_restarts_total",
                "Bounded in-process fit restarts (restore + "
                "fast-forward) taken after step failures."),
        }
    return _TRAINER_METRICS


def make_loss(name: str) -> Callable:
    import jax.numpy as jnp
    import optax

    if name == "softmax_cross_entropy":
        def loss(logits, labels, weights):
            l = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels.astype(jnp.int32))
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    elif name == "sigmoid_cross_entropy":
        def loss(logits, labels, weights):
            l = optax.sigmoid_binary_cross_entropy(logits[..., 0], labels)
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    elif name == "squared_error":
        def loss(logits, labels, weights):
            l = jnp.square(logits[..., 0] - labels)
            return jnp.sum(l * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    else:
        raise ValueError(f"unknown loss {name!r}; have {LOSSES}")
    return loss


def make_optimizer(name: str, learning_rate: float, momentum: float = 0.9,
                   weight_decay: float = 1e-4, clip_norm: float = 0.0):
    import optax
    if name == "sgd":
        tx = optax.sgd(learning_rate)
    elif name == "momentum":
        tx = optax.sgd(learning_rate, momentum=momentum)
    elif name == "adam":
        tx = optax.adam(learning_rate)
    elif name == "adamw":
        tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {name!r}; have {OPTIMIZERS}")
    if clip_norm and clip_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
    return tx


class NNLearner(Estimator, HasLabelCol, HasFeaturesCol):
    """Train an NNFunction on a labeled frame; returns an NNModel."""

    features_col = Param("features", "input column (vectors or images)", ptype=str)
    label_col = Param("label", "label column", ptype=str)
    weight_col = Param(None, "optional per-row weight column", ptype=str)
    arch = Param(None, "architecture config dict (builder + kwargs)", ptype=dict)
    model = Param(None, "optional warm-start NNFunction", complex=True)
    loss = Param("softmax_cross_entropy", "training loss",
                 validator=in_set(*LOSSES))
    optimizer = Param("momentum", "optimizer", validator=in_set(*OPTIMIZERS))
    learning_rate = Param(0.1, "peak learning rate", ptype=float)
    momentum = Param(0.9, "sgd momentum", ptype=float)
    weight_decay = Param(1e-4, "adamw weight decay", ptype=float)
    clip_norm = Param(0.0, "global-norm gradient clipping (0 = off); "
                      "guards deep-net fits against divergence at "
                      "aggressive peak learning rates", ptype=float)
    epochs = Param(10, "passes over the data", ptype=int)
    batch_size = Param(256, "global batch size", ptype=int)
    warmup_steps = Param(0, "linear LR warmup steps", ptype=int)
    cosine_decay = Param(True, "cosine-decay LR to 0 over training", ptype=bool)
    seed = Param(0, "init/shuffle seed", ptype=int)
    mesh_shape = Param(None, "mesh axes dict, e.g. {'data': -1}; a "
                       "'model' axis > 1 turns on tensor parallelism "
                       "(params + optimizer state sharded per "
                       "parallel/dist rules, XLA inserts the "
                       "collectives)", ptype=dict)
    checkpoint_dir = Param(None, "sharded step-checkpoint directory "
                           "(io/checkpoint native store)", ptype=str)
    checkpoint_every = Param(0, "steps between checkpoints (0 = off)", ptype=int)
    push_gateway_url = Param(None, "optional metrics remote-write URL "
                             "(Prometheus Pushgateway job path or any "
                             "endpoint accepting the text exposition): "
                             "a MetricsPusher POSTs the process "
                             "registry there on an interval during "
                             "fit, with a final flush when the fit "
                             "ends — a batch fit's telemetry reaches a "
                             "LIVE Prometheus even though the job "
                             "exits between scrapes (checkpoint-side "
                             ".prom snapshots remain the on-disk "
                             "fallback)", ptype=str)
    push_interval_s = Param(30.0, "seconds between remote-write pushes",
                            ptype=float, validator=in_range(lo=1.0))
    max_restarts = Param(2, "bounded in-process auto-restarts: when a "
                         "train step fails and checkpointing is "
                         "configured, restore the latest step "
                         "checkpoint and resume the SAME shuffle "
                         "stream (deterministic fast-forward); after "
                         "this many restores the error propagates — a "
                         "persistent fault must fail the fit, not loop "
                         "it", ptype=int)
    fault_injector = Param(None, "chaos-test hook: callable(global_step)"
                           " invoked before each host-loop step; "
                           "exceptions it raises exercise the bounded-"
                           "restart path (see testing.faults.FaultPlan."
                           "step_fault)", complex=True)
    log_every = Param(50, "steps between loss logs (0 = off)", ptype=int)
    device_resident = Param(False, "upload the dataset to the device ONCE "
                            "and run each epoch as one scanned device "
                            "program (batches gathered on device from an "
                            "uploaded permutation): one dispatch + one "
                            "loss fetch per epoch instead of a transfer "
                            "per step — the fit shape for high-latency "
                            "host<->device links (integer image data "
                            "stays integer on the wire and is "
                            "normalized on device). Single-data-shard "
                            "fits only; falls back otherwise", ptype=bool)
    augment = Param("none", "on-device per-batch augmentation: flip_crop "
                    "= random horizontal flip + random 4px translate "
                    "(the standard CIFAR recipe), applied inside the "
                    "jitted step", validator=in_set("none", "flip_crop"))

    # -- jitted step construction ------------------------------------------

    def build_train_step(self, module, tx, loss_fn):
        """(params, opt_state, batch) -> (params, opt_state, loss), jittable."""
        import jax

        def step(params, opt_state, x, y, w):
            def objective(p):
                logits = module.apply(p, x, train=True)
                return loss_fn(logits, y, w)

            loss, grads = jax.value_and_grad(objective)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return step

    @staticmethod
    def _augment_flip_crop(key, xb):
        """Random horizontal flip + random 4px translate, on device."""
        import jax
        import jax.numpy as jnp
        b, hgt, wid = xb.shape[0], xb.shape[1], xb.shape[2]
        k1, k2 = jax.random.split(key)
        flip = jax.random.bernoulli(k1, 0.5, (b,))
        xb = jnp.where(flip[:, None, None, None], xb[:, :, ::-1, :], xb)
        pad = 4
        padded = jnp.pad(xb, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                         mode="reflect")
        offs = jax.random.randint(k2, (b, 2), 0, 2 * pad + 1)

        def crop(img, o):
            return jax.lax.dynamic_slice(
                img, (o[0], o[1], 0), (hgt, wid, img.shape[-1]))

        return jax.vmap(crop)(padded, offs)

    def _fit_device_resident(self, x, y, w, fn, module, bs, tx, loss_fn):
        """Whole-epoch scanned training with a device-resident dataset.

        The per-step host loop below pays one host->device batch
        transfer and one dispatch per step — hundreds of link
        round-trips per epoch on a tunneled chip. Here the dataset
        (kept uint8 if it arrived uint8: 4x fewer link bytes than f32)
        is uploaded once, each epoch's shuffled batch indices are one
        small int32 upload, and ``lax.scan`` gathers + steps entirely
        on device: one dispatch and one loss fetch per epoch. The same
        shape as the fused GBDT fit (`gbdt/tree.py::boost_loop_device`).
        """
        import jax
        import jax.numpy as jnp

        # ONLY uint8 is treated as image bytes (x/255 + a uint8-tagged
        # scorer); other integer dtypes are plain numerics cast to f32 —
        # scaling counts by 1/255 and round-tripping them through uint8
        # at scoring time would silently corrupt values > 255
        is_int = x.dtype == np.uint8
        scale = np.float32(1.0 / 255.0) if is_int else np.float32(1.0)
        # datasets smaller than the batch keep working (the host loop
        # pads ragged batches; here the batch shrinks to the data)
        bs = min(bs, len(x))
        steps_per_epoch = max(len(x) // bs, 1)
        x_dev = jnp.asarray(x)
        y_dev = jnp.asarray(y)
        w_dev = jnp.asarray(w)
        step_fn = self.build_train_step(module, tx, loss_fn)
        aug = self.augment

        def epoch_fn(params, opt_state, key, perm):
            def body(carry, idx):
                p, o, k = carry
                k, k_aug = jax.random.split(k)
                xb = x_dev[idx].astype(jnp.float32) * scale
                if aug == "flip_crop":
                    xb = self._augment_flip_crop(k_aug, xb)
                p, o, loss = step_fn(p, o, xb, y_dev[idx], w_dev[idx])
                return (p, o, k), loss

            (params, opt_state, _), losses = jax.lax.scan(
                body, (params, opt_state, key), perm)
            return params, opt_state, losses

        epoch_jit = jax.jit(epoch_fn, donate_argnums=(0, 1))

        params = jax.device_put(fn.params)
        opt_state = tx.init(params)
        rng = np.random.default_rng(self.seed)
        n_use = steps_per_epoch * bs
        from mmlspark_tpu.core.tracing import ambient_tracer
        TRACER = ambient_tracer()
        for epoch in range(self.epochs):
            perm = rng.permutation(len(x))[:n_use].astype(np.int32) \
                .reshape(steps_per_epoch, bs)
            key = jax.random.PRNGKey(self.seed * 100003 + epoch)
            # the scanned fit's unit of work is the EPOCH (one dispatch
            # + one loss fetch), so that is its span granularity
            with TRACER.span("train_epoch", route="trainer",
                             epoch=epoch + 1,
                             steps=int(steps_per_epoch)):
                params, opt_state, losses = epoch_jit(
                    params, opt_state, key, jnp.asarray(perm))
            if self.log_every:
                print(f"[NNLearner] epoch {epoch + 1}/{self.epochs} "
                      f"mean loss {float(jnp.mean(losses)):.5f}")

        trained = NNFunction(arch=dict(fn.arch),
                             params=jax.device_get(params))
        # an integer-trained model's scorer must keep the same input
        # convention (uint8 in, /255 on device) or every consumer would
        # silently feed 0-255 floats into a net trained on [0, 1]
        extra = {"input_dtype": "uint8"} if is_int else {}
        return NNModel(model=trained, input_col=self.features_col,
                       output_col="scores", **extra)

    def _schedule(self, steps_per_epoch: int):
        import optax
        warmup = max(self.warmup_steps, 1)
        total = max(self.epochs * steps_per_epoch, warmup + 1)
        if self.cosine_decay:
            return optax.warmup_cosine_decay_schedule(
                0.0, self.learning_rate, warmup, total)
        if self.warmup_steps:
            return optax.linear_schedule(0.0, self.learning_rate,
                                         self.warmup_steps)
        return self.learning_rate

    # -- fit ----------------------------------------------------------------

    def fit(self, df: DataFrame) -> NNModel:
        if not self.push_gateway_url:
            return self._fit(df)
        # remote-write rides the whole fit: periodic pushes while the
        # host loop runs, one final flush in the finally (success OR
        # failure — a crashed fit's last counters are exactly the
        # telemetry worth having). Step/egress spans carry trace
        # context on any HTTP the fit fans out (io/http injects the
        # ambient train_step span), so pushed exemplars and captured
        # step traces stay correlated.
        from mmlspark_tpu.core.telemetry import MetricsPusher
        with MetricsPusher(self.push_gateway_url,
                           interval_s=self.push_interval_s):
            return self._fit(df)

    def _fit(self, df: DataFrame) -> NNModel:
        import jax
        import optax

        from mmlspark_tpu.models.nn import _stack_column
        # _stack_column preserves source dtype; training computes in
        # f32, but a device-resident fit keeps integer image data
        # integer ON THE LINK and normalizes on device
        x = _stack_column(df[self.features_col])
        # uint8 survives for BOTH paths (each normalizes /255 and tags
        # the scorer identically — a perf flag must never change the
        # learned function); every other dtype trains as f32
        if x.dtype != np.uint8:
            x = x.astype(np.float32, copy=False)
        y = np.asarray(df[self.label_col])
        w = (np.asarray(df[self.weight_col], dtype=np.float32)
             if self.weight_col else np.ones(len(y), dtype=np.float32))

        fn = self.model or NNFunction.init(self.arch, x.shape[1:],
                                           seed=self.seed)
        module = fn.module()

        from mmlspark_tpu.parallel.topology import in_single_device_scope
        if in_single_device_scope():
            # pinned-trial context (TuneHyperparameters trial_devices):
            # train on the thread's default device only
            dev = jax.config.jax_default_device or jax.local_devices()[0]
            mesh = build_mesh(MeshSpec.from_dict({"data": 1}),
                              devices=[dev])
        else:
            mesh = build_mesh(MeshSpec.from_dict(self.mesh_shape)
                              if self.mesh_shape else None)
        n_data = mesh.shape.get("data", 1)
        bs = max(self.batch_size - self.batch_size % n_data, n_data)
        steps_per_epoch = max(len(x) // bs, 1)

        tx = make_optimizer(self.optimizer, self._schedule(steps_per_epoch),
                            self.momentum, self.weight_decay,
                            self.clip_norm)
        loss_fn = make_loss(self.loss)
        if self.device_resident and n_data == 1 \
                and self._checkpoint_manager() is None:
            return self._fit_device_resident(x, y, w, fn, module, bs,
                                             tx, loss_fn)
        if self.augment != "none":
            import warnings
            warnings.warn(
                "augment is applied by the device-resident scanned fit "
                "only; this fit takes the per-step host loop "
                f"(device_resident={self.device_resident}, data shards="
                f"{n_data}, checkpointing="
                f"{self.checkpoint_dir is not None}) and trains WITHOUT "
                "augmentation", stacklevel=2)
        was_int = x.dtype == np.uint8        # image bytes only, as above
        if was_int:
            x = x.astype(np.float32) / 255.0   # host fallback normalizes
        step = jax.jit(self.build_train_step(module, tx, loss_fn),
                       donate_argnums=(0, 1))

        # state placement: replicated on a pure-data mesh (byte-for-byte
        # the pre-TP behavior — every spec degenerates to P() when no
        # model axis exists), model-sharded per the dist rule otherwise;
        # optimizer moments land with their param's layout because the
        # rule is shape-driven. The jitted step donates both trees, so
        # the sharded update happens in place in device memory.
        from mmlspark_tpu.parallel import dist as _dist
        repl = _dist.state_shardings(fn.params, mesh)
        params = jax.device_put(fn.params, repl)
        opt_state = tx.init(params)
        opt_repl = _dist.state_shardings(opt_state, mesh)
        opt_state = jax.device_put(opt_state, opt_repl)

        start_step = 0
        mngr = self._checkpoint_manager()
        template = None
        if mngr is not None:
            # host-side structure template, captured BEFORE any step
            # runs: the jitted step donates its params/opt_state
            # buffers, so after a mid-step fault the live buffers may
            # already be invalidated — restores must not depend on them
            template = {"params": jax.device_get(params),
                        "opt_state": jax.device_get(opt_state)}
        if mngr is not None and mngr.latest_step() is not None:
            raw_params, raw_opt, start_step = self._restore(mngr, template)
            params = jax.device_put(raw_params, repl)
            opt_state = jax.device_put(raw_opt, opt_repl)

        # -- fault-tolerant fit: a step failure (preempted chip, injected
        # chaos fault, failed checkpoint write) restores the latest
        # checkpoint and re-enters the SAME deterministic shuffle stream
        # (the fast-forward below), bounded by max_restarts so a
        # persistent fault still fails the fit
        restarts = 0
        while True:
            try:
                params, opt_state = self._host_loop(
                    x, y, w, step, mesh, params, opt_state, start_step,
                    steps_per_epoch, bs, n_data, mngr)
                break
            except Exception as e:  # noqa: BLE001 — classified below
                if isinstance(e, NotImplementedError):
                    raise   # a permanent capability gap, not a fault
                if mngr is None or restarts >= self.max_restarts:
                    raise
                restarts += 1
                _metrics()["restarts"].inc()
                latest = mngr.latest_step()
                print(f"[NNLearner] step failed ({type(e).__name__}: {e});"
                      f" restoring "
                      f"{'step ' + str(latest) if latest is not None else 'init'}"
                      f" (restart {restarts}/{self.max_restarts})")
                if latest is None:
                    params = jax.device_put(fn.params, repl)
                    opt_state = jax.device_put(tx.init(params), opt_repl)
                    start_step = 0
                else:
                    raw_params, raw_opt, start_step = \
                        self._restore(mngr, template)
                    params = jax.device_put(raw_params, repl)
                    opt_state = jax.device_put(raw_opt, opt_repl)

        trained = NNFunction(arch=dict(fn.arch), params=jax.device_get(params))
        # keep the training-time input convention (see _fit_device_resident)
        extra = {"input_dtype": "uint8"} if was_int else {}
        return NNModel(model=trained, input_col=self.features_col,
                       output_col="scores", **extra)

    def _host_loop(self, x, y, w, step, mesh, params, opt_state,
                   start_step, steps_per_epoch, bs, n_data, mngr):
        """One attempt at the per-step host loop, resumable at
        ``start_step``: the shuffle stream is regenerated from the seed
        and already-done steps are skipped, so every attempt sees the
        identical batch sequence (restart N reaches the same params an
        uninterrupted run does)."""
        import jax
        from mmlspark_tpu.parallel import dist as _dist

        from mmlspark_tpu.core.tracing import ambient_tracer
        TRACER = ambient_tracer()

        rng = np.random.default_rng(self.seed)
        metrics = _metrics()
        m_step, m_eps = metrics["step_ms"], metrics["examples_per_sec"]
        global_step = 0
        # per-attempt dispatch-shape memory: a batch shape this attempt
        # has not dispatched yet forces a jit retrace, and the step's
        # span marks it (recompile=True) so a captured slow step says
        # WHY it was slow (the ragged tail batch is the usual culprit)
        shapes_seen: set = set()
        # bound the number of dispatched-but-unfinished steps: an
        # unthrottled loop queues every step at once, and XLA:CPU's
        # cross-device collective rendezvous can deadlock when executions
        # from many run_ids oversubscribe the shared thread pool (the
        # virtual 8-device test mesh hits this). A window of 2 keeps
        # host/device pipelining on real chips while serializing enough.
        from collections import deque
        inflight: deque = deque()
        for epoch in range(self.epochs):
            order = rng.permutation(len(x))
            for s in range(steps_per_epoch):
                global_step += 1
                if global_step <= start_step:
                    continue  # fast-forward after resume (same shuffle stream)
                # one root span per step (route "trainer"): a chaos
                # fault raised inside finishes it with status=error, so
                # failed steps are tail-captured with their timeline;
                # the step_ms observe below runs inside the span, so
                # the histogram's exemplar links a slow bucket straight
                # to the captured step trace
                with TRACER.span("train_step", route="trainer",
                                 step=global_step,
                                 epoch=epoch + 1) as sp:
                    if self.fault_injector is not None:
                        self.fault_injector(global_step)
                    t_step = time.perf_counter()
                    idx = order[s * bs:(s + 1) * bs]
                    # ragged tail: pad to the data-axis multiple, zero
                    # the pad rows' weights so they contribute nothing
                    # to the loss
                    xp, n_real = pad_to_multiple(x[idx], n_data)
                    yp, _ = pad_to_multiple(y[idx], n_data)
                    wp, _ = pad_to_multiple(w[idx], n_data)
                    if n_real < len(wp):
                        wp = wp.copy()
                        wp[n_real:] = 0.0
                    recompile = xp.shape not in shapes_seen
                    if recompile:
                        shapes_seen.add(xp.shape)
                    t_disp = TRACER.clock.now()
                    # data-sharded global placement. Multi-process: the
                    # shuffle stream is seed-identical on every host, so
                    # each host contributes ONLY its row slice of the
                    # padded global batch and parallel/dist assembles —
                    # feeding the full batch would duplicate every row
                    # n_proc times and silently change the gradient
                    if jax.process_count() > 1:
                        plo, phi = _dist.process_local_rows(len(xp), mesh)
                        xp, yp, wp = xp[plo:phi], yp[plo:phi], wp[plo:phi]
                    placed, _ = _dist.put_batch(
                        {"x": xp, "y": yp, "w": wp}, mesh)
                    xb, yb, wb = placed["x"], placed["y"], placed["w"]
                    params, opt_state, loss = step(params, opt_state,
                                                   xb, yb, wb)
                    inflight.append(loss)
                    if len(inflight) > 2:
                        inflight.popleft().block_until_ready()
                    # dispatch is async: this child is transfer +
                    # enqueue time, plus the periodic device block when
                    # the in-flight window fills (and the whole trace/
                    # compile, on a recompile=True step)
                    TRACER.add("step_dispatch", t_disp,
                               TRACER.clock.now(), parent=sp,
                               recompile=recompile, batch=int(len(xp)))
                    dt = time.perf_counter() - t_step
                    m_step.observe(dt * 1000.0)
                    if dt > 0:
                        m_eps.observe(n_real / dt)
                    if self.log_every and global_step % self.log_every == 0:
                        print(f"[NNLearner] step {global_step} "
                              f"epoch {epoch + 1}/{self.epochs} "
                              f"loss {float(loss):.5f}")
                    if (mngr is not None and self.checkpoint_every
                            and global_step % self.checkpoint_every == 0):
                        self._checkpoint(mngr, global_step, params,
                                         opt_state)
        if mngr is not None:
            self._checkpoint(mngr, global_step, params, opt_state)
            mngr.wait_until_finished()
        return params, opt_state

    # -- sharded step checkpointing ----------------------------------------

    def _checkpoint_manager(self):
        if not self.checkpoint_dir:
            return None
        import jax
        if jax.process_count() > 1:
            # the native store is single-process (save_sharded would
            # raise at the FIRST checkpoint, which the restart loop
            # would then misread as a transient step fault and re-fit
            # from scratch max_restarts times): fail before any
            # training work is spent
            raise NotImplementedError(
                "checkpoint_dir is single-process for now: the native "
                "sharded store cannot write one directory from "
                "multiple hosts (see io/checkpoint.save_sharded)")
        from mmlspark_tpu.io import checkpoint as _ckpt
        return _ckpt.manager(self.checkpoint_dir)

    def _checkpoint(self, mngr, step_num: int, params, opt_state) -> None:
        from mmlspark_tpu.core.tracing import ambient_tracer
        TRACER = ambient_tracer()
        with TRACER.span("checkpoint_save", step=step_num), \
                _metrics()["ckpt_save_ms"].time():
            # the live trees are written shard-by-shard (replicated
            # leaves once, model-sharded leaves per slice) — no host
            # gather; the digest manifest lands last
            mngr.save(step_num,
                      {"params": params, "opt_state": opt_state})
        # a scrape rides every checkpoint: batch fits usually exit (or
        # are preempted) before any Prometheus scrape, so the registry
        # state lands next to the step it describes — under telemetry/
        # (NOT the checkpoint root: the manager owns that namespace's
        # step listing). Best-effort: telemetry must never fail a save.
        try:
            from mmlspark_tpu.core.telemetry import snapshot_registries
            from mmlspark_tpu.io import fs as _fs
            snapshot_registries(_fs.join(self.checkpoint_dir, "telemetry"),
                                tag=f"step{step_num:08d}", keep=8)
        except Exception:  # noqa: BLE001
            from mmlspark_tpu.core.logs import get_logger
            get_logger("trainer").warning(
                "checkpoint metrics snapshot failed", exc_info=True)

    def _restore(self, mngr, template):
        """Restore the latest step against a host-side (params,
        opt_state) structure template, so optax NamedTuple states
        round-trip intact. The template must predate the first step:
        the donated live buffers are not safe to read after a fault.
        Host arrays come back; the caller re-places them with the
        current mesh's shardings — which may differ from the saving
        run's (topology-change resume)."""
        from mmlspark_tpu.core.tracing import ambient_tracer
        TRACER = ambient_tracer()
        latest = mngr.latest_step()
        with TRACER.span("checkpoint_restore", step=latest), \
                _metrics()["ckpt_restore_ms"].time():
            restored = mngr.restore(latest, template)
        print(f"[NNLearner] resumed from step {latest}")
        return restored["params"], restored["opt_state"], latest
