#!/usr/bin/env bash
# Tag-gated test driver.
#
# Parity: the reference selects scalatest tags via $TESTS
# (`src/project/build.scala:119-131`, `tools/tests/tags.sh`:
# "-extended", "+linuxonly", ...). Here the same contract over pytest
# markers:
#
#   TESTS="-slow"   ./tools/run_tests.sh     # skip the slow quality gates
#   TESTS="+slow"   ./tools/run_tests.sh     # only the slow quality gates
#   ./tools/run_tests.sh tests/test_gbdt.py  # extra args pass through
set -euo pipefail
cd "$(dirname "$0")/.."

# pytest keeps only the LAST -m flag, so all tag specs must be joined
# into one marker expression
EXPR=""
for tag in ${TESTS:-}; do
  case "$tag" in
    -*) part="not ${tag:1}" ;;
    +*) part="${tag:1}" ;;
    *)  echo "unknown tag spec '$tag' (use +name / -name)" >&2; exit 2 ;;
  esac
  if [ -n "$EXPR" ]; then EXPR="$EXPR and $part"; else EXPR="$part"; fi
done

if [ -n "$EXPR" ]; then
  exec python -m pytest tests/ -q -m "$EXPR" "$@"
fi
exec python -m pytest tests/ -q "$@"
