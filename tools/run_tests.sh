#!/usr/bin/env bash
# Tag-gated test driver.
#
# Parity: the reference selects scalatest tags via $TESTS
# (`src/project/build.scala:119-131`, `tools/tests/tags.sh`:
# "-extended", "+linuxonly", ...). Here the same contract over pytest
# markers:
#
#   TESTS="-slow"   ./tools/run_tests.sh     # skip the slow quality gates
#   TESTS="+slow"   ./tools/run_tests.sh     # only the slow quality gates
#   ./tools/run_tests.sh tests/test_gbdt.py  # extra args pass through
set -euo pipefail
cd "$(dirname "$0")/.."

MARKER_ARGS=()
for tag in ${TESTS:-}; do
  case "$tag" in
    -*) MARKER_ARGS+=(-m "not ${tag:1}") ;;
    +*) MARKER_ARGS+=(-m "${tag:1}") ;;
    *)  echo "unknown tag spec '$tag' (use +name / -name)" >&2; exit 2 ;;
  esac
done

exec python -m pytest tests/ -q "${MARKER_ARGS[@]}" "$@"
