"""Measure device-parallel tuning vs the shared-device thread pool.

SURVEY §2.9 row 6: the reference's TuneHyperparameters runs trials on a
driver thread pool contending for shared Spark executors; the TPU-first
version pins each trial to its own chip (``trial_devices``, now ``auto``
— on whenever the host has >1 device). This records the wall-clock
comparison artifact on the virtual 8-device CPU mesh.

Two distinct effects add up, and the artifact records which host shape
measured them:

- On ANY host (even 1 core — see the committed artifact): pinning
  removes cross-thread contention on a single device's execution
  stream (concurrent trials interleaving dispatches against one device
  serialize far worse than independent per-device queues).
- On multi-core hosts, the virtual devices additionally run trial
  compute in true parallel, compounding the win (the reason
  tests/test_automl.py's wall-clock assertion is gated on core count).

    python tools/bench_tuning_parallel.py

Writes ``docs/artifacts/tuning_parallel.json`` (n_cores included so
the number is interpretable).
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mmlspark_tpu.parallel.topology import use_cpu_devices  # noqa: E402

use_cpu_devices(8)


def main() -> None:
    from mmlspark_tpu.core.dataframe import DataFrame, obj_col
    from mmlspark_tpu.gbdt import GBDTClassifier
    from mmlspark_tpu.automl.train import TrainClassifier
    from mmlspark_tpu.automl.tune import (
        DiscreteHyperParam, TuneHyperparameters)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 12))
    y = (X[:, 0] + X[:, 1] * 0.5 + 0.4 * rng.normal(size=2000) > 0
         ).astype(np.int64)
    df = DataFrame({"features": obj_col(list(X)), "label": y})
    space = {"num_leaves": DiscreteHyperParam([7, 15, 31, 63]),
             "num_iterations": DiscreteHyperParam([20, 40])}

    n_cores = len(os.sched_getaffinity(0))
    out = {"n_cores": n_cores, "n_devices": 8,
           "mechanism": ("dispatch-contention relief only (1 core)"
                         if n_cores == 1 else
                         "contention relief + parallel trial compute"),
           "note": ("measured on a 1-core host with 8 VIRTUAL CPU devices: "
                    "the speedup is dispatch-contention relief, NOT "
                    "parallel hardware; the real multi-chip claim is "
                    "pending pod hardware" if n_cores == 1 else
                    "virtual CPU devices on a multi-core host; the real "
                    "multi-chip claim is pending pod hardware")}
    for key, td in (("pinned_devices_s", True), ("shared_device_s", False)):
        t0 = time.perf_counter()
        TuneHyperparameters(
            models=[TrainClassifier(model=GBDTClassifier(min_data_in_leaf=5),
                                    label_col="label")],
            param_space=space, evaluation_metric="accuracy",
            num_folds=2, num_runs=6, parallelism=4, seed=1,
            trial_devices=td).fit(df)
        out[key] = round(time.perf_counter() - t0, 2)
    out["speedup"] = round(out["shared_device_s"]
                           / max(out["pinned_devices_s"], 1e-9), 2)

    path = os.path.join(REPO, "docs", "artifacts", "tuning_parallel.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
