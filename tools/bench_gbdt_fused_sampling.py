"""Wall-clock evidence: sampled fused GBDT fits vs the plain fused fit.

Bagging/goss/feature-fraction now ride the fused device scan as device
RNG (gbdt/tree.py::boost_loop_device), so a sampled early-stopping fit
still pays exactly ONE host fetch — this records that the sampling
machinery costs little wall-clock vs the plain fused fit (the
reference's native loop serves every boosting mode with no per-mode
overhead either, `TrainUtils.scala:95-146`).

    python tools/bench_gbdt_fused_sampling.py

Writes ``docs/artifacts/gbdt_fused_sampling.json``.
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    from mmlspark_tpu.gbdt.booster import Booster, BoosterParams
    from mmlspark_tpu.core.environment import environment_info

    rng = np.random.default_rng(0)
    n, f = 4096, 100
    X = rng.normal(size=(n, f))
    y = X[:, :5].sum(axis=1) + 0.3 * rng.normal(size=n) + 5.0
    Xv, yv = X[3500:], y[3500:]
    Xt, yt = X[:3500], y[:3500]

    common = dict(objective="regression", num_iterations=40, num_leaves=15,
                  early_stopping_round=10, seed=0)
    configs = {
        "plain": BoosterParams(**common),
        "bagged": BoosterParams(bagging_fraction=0.8, bagging_freq=2,
                                **common),
        "goss": BoosterParams(boosting_type="goss", **common),
        "feature_fraction": BoosterParams(feature_fraction=0.8, **common),
    }
    out = {}
    for name, p in configs.items():
        fit = lambda: Booster.train(p, Xt, yt, valid_sets=[(Xv, yv)])
        fit()                                    # warm: bin + compile
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            fit()
            times.append(time.perf_counter() - t0)
        out[name + "_s"] = round(float(np.median(times)), 3)
    for name in ("bagged", "goss", "feature_fraction"):
        out[name + "_vs_plain"] = round(out[name + "_s"] / out["plain_s"], 2)
    info = environment_info()
    out["chip"] = {k: info[k] for k in ("platform", "device_kind")}

    path = os.path.join(REPO, "docs", "artifacts",
                        "gbdt_fused_sampling.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
