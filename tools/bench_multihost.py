#!/usr/bin/env python
"""Multi-device scaling + parity harness — the ``multihost_scaling_v1``
evidence (ISSUE 10).

One self-contained process that builds 1/2/4/8-device meshes (CPU
``--xla_force_host_platform_device_count`` simulation by default; the
same code runs unchanged on real chips) and measures the distributed
execution layer end to end:

* **A/B parity on fixed seeds** — a pjit data x tensor-parallel
  NNLearner fit must reproduce the single-device fit's scores, and the
  tensor-parallel decoder must emit the single-device greedy token
  sequence (``parity``).
* **Devices-vs-throughput curve** — a model-parallel-friendly
  (wide-MLP) train step compiled per mesh size, timed as one scanned
  device program with the long/short slope trick (``curve``). On CPU,
  ``--xla_cpu_multi_thread_eigen=false`` pins each virtual device to
  one worker thread so "devices" are the unit of parallelism — the
  honest simulation of fixed-compute chips.
* **Zero steady-state recompiles in tensor-parallel serving** — a live
  ``ServingServer`` dispatching a ``tensor_parallel=2`` model and a
  TP ``TransformerDecoder`` both hold their post-warmup compile
  counts flat under traffic (``serving``).
* **Sharded-checkpoint topology drill** — train state saved from a
  2x2 mesh restores bit-identically onto 4x1 and a single device,
  digest manifest verified (``checkpoint``).

Usage::

    python tools/bench_multihost.py --smoke     # CI gate: asserts, exits 1 on violation
    python tools/bench_multihost.py --json      # print the evidence JSON (bench.py consumes)
    python tools/bench_multihost.py --devices 8 # simulated device count
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _ensure_devices(n: int) -> None:
    """Must run before the jax backend initializes."""
    from mmlspark_tpu.parallel.topology import bump_host_device_count
    flags = bump_host_device_count(os.environ.get("XLA_FLAGS", ""), n)
    if "xla_cpu_multi_thread_eigen" not in flags:
        # one worker thread per virtual device: the devices, not the
        # shared eigen pool, are the unit of parallelism — otherwise a
        # "1-device" baseline silently uses every core and the curve
        # measures nothing
        flags += " --xla_cpu_multi_thread_eigen=false"
    os.environ["XLA_FLAGS"] = flags
    if os.environ.get("MMLSPARK_TPU_BENCH_TPU") != "1":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------


def _tp_mesh_shape() -> dict:
    """The biggest data x model=2 mesh this host can build (the
    harness must degrade to 2 devices — and report, not crash, on 1)."""
    import jax
    n = len(jax.devices())
    if n >= 4:
        return {"data": 2, "model": 2}
    if n >= 2:
        return {"data": 1, "model": 2}
    return {"data": 1}


def parity_check(steps_epochs: int = 5) -> dict:
    """Sharded-vs-single-device A/B on fixed seeds."""
    import numpy as np
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.trainer import NNLearner
    from mmlspark_tpu.models import transformer as T
    from mmlspark_tpu.serving.decode import TransformerDecoder
    from mmlspark_tpu.parallel import dist

    rng = np.random.default_rng(42)
    n = 256
    x = np.concatenate([rng.normal(-2.0, size=(n, 4)),
                        rng.normal(2.0, size=(n, 4))]).astype(np.float32)
    y = np.concatenate([np.zeros(n), np.ones(n)]).astype(np.int64)
    perm = rng.permutation(len(x))
    df = DataFrame({"features": x[perm], "label": y[perm]})
    common = dict(arch={"builder": "mlp", "hidden": [16], "num_outputs": 2},
                  optimizer="adam", learning_rate=0.01,
                  epochs=steps_epochs, batch_size=64, log_every=0, seed=3)
    m1 = NNLearner(mesh_shape={"data": 1}, **common).fit(df)
    m2 = NNLearner(mesh_shape=_tp_mesh_shape(), **common).fit(df)
    s1 = m1.transform(df)["scores"]
    s2 = m2.transform(df)["scores"]
    train_diff = float(np.abs(s1 - s2).max())

    cfg = T.TransformerConfig(vocab=128, d_model=32, n_heads=4, d_head=8,
                              d_ff=64, n_stages=1, layers_per_stage=2)
    params = T.init_params(cfg, seed=0)
    prompt = np.asarray([5, 9, 77, 3], np.int32)

    def greedy(dec, n_tokens=10):
        seq = [dec.prefill(0, prompt)]
        toks = np.zeros(dec.n_slots, np.int32)
        pos = np.zeros(dec.n_slots, np.int32)
        toks[0], pos[0] = seq[0], len(prompt)
        for _ in range(n_tokens):
            out = dec.step(toks, pos)
            seq.append(int(out[0]))
            toks[0] = out[0]
            pos[0] += 1
        return seq

    d1 = TransformerDecoder(params, cfg, n_slots=4, max_len=64)
    d1.warmup()
    mesh = dist.train_mesh(_tp_mesh_shape())
    d2 = TransformerDecoder(params, cfg, n_slots=4, max_len=64, mesh=mesh)
    base = d2.warmup()
    t1, t2 = greedy(d1), greedy(d2)
    return {
        "train_score_max_diff": train_diff,
        "train_parity_ok": train_diff < 1e-3,
        "decode_tokens_equal": t1 == t2,
        "decode_tp_recompiles": d2.n_compiles() - base,
        "ok": (train_diff < 1e-3 and t1 == t2
               and d2.n_compiles() == base),
    }


# ---------------------------------------------------------------------------
# scaling curve
# ---------------------------------------------------------------------------


def scaling_curve(counts=(1, 2, 4, 8), d_model: int = 512,
                  d_ff: int = 2048, batch: int = 32,
                  n_long: int = 40, repeats: int = 3) -> list:
    """Steps/s of a model-parallel-friendly train step per device count.

    The step is one jitted fwd+bwd+SGD over a wide MLP with params
    sharded over ``model`` (the dist rule) — the shape whose matmuls
    split cleanly across the axis. Timing is the long/short scanned-
    chain slope (one dispatch, data-dependent iterations), the same
    methodology every device-side bench in bench.py uses."""
    import functools
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.parallel import dist

    rng = np.random.default_rng(0)
    params = {"w1": (rng.normal(size=(d_model, d_ff)) * 0.02
                     ).astype(np.float32),
              "w2": (rng.normal(size=(d_ff, d_model)) * 0.02
                     ).astype(np.float32)}
    x = rng.normal(size=(batch, d_model)).astype(np.float32)
    y = rng.normal(size=(batch, d_model)).astype(np.float32)

    def step(p, xb, yb):
        def loss_fn(q):
            h = jax.nn.relu(xb @ q["w1"])
            return jnp.mean((h @ q["w2"] - yb) ** 2)
        l, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree.map(lambda a, b: a - 0.01 * b, p, g), l

    curve = []
    n_avail = len(jax.devices())
    for n_dev in counts:
        if n_dev > n_avail:
            continue
        mesh = dist.train_mesh({"data": 1, "model": n_dev},
                               devices=jax.devices()[:n_dev])
        p = dist.shard_state(params, mesh)
        xb = jax.device_put(x, dist.batch_shardings(mesh))
        yb = jax.device_put(y, dist.batch_shardings(mesh))

        @functools.partial(jax.jit, static_argnames="n")
        def chain(p, n, xb=xb, yb=yb):
            def body(c, _):
                c, l = step(c, xb, yb)
                return c, l
            _, ls = jax.lax.scan(body, p, None, length=n)
            return ls

        chain(p, n=2).block_until_ready()

        def run(k, chain=chain, p=p):
            t0 = time.perf_counter()
            chain(p, n=k).block_until_ready()
            return time.perf_counter() - t0

        t_long = min(run(n_long) for _ in range(repeats))
        t_short = min(run(2) for _ in range(repeats))
        sec = max((t_long - t_short) / (n_long - 2), 1e-9)
        curve.append({"devices": n_dev,
                      "steps_per_s": round(1.0 / sec, 2),
                      "ms_per_step": round(sec * 1000.0, 4)})
    return curve


# ---------------------------------------------------------------------------
# tensor-parallel serving: zero steady-state recompiles
# ---------------------------------------------------------------------------


def serving_recompile_check(n_requests: int = 32) -> dict:
    """Drive a live TP server past warmup; the compile set must not grow."""
    import urllib.request
    import numpy as np
    from mmlspark_tpu.models.function import NNFunction
    from mmlspark_tpu.models.nn import NNModel
    from mmlspark_tpu.serving.server import ServingServer

    import jax
    if len(jax.devices()) < 2:
        return {"skipped": "tensor parallelism needs >= 2 devices",
                "ok": True}
    fn = NNFunction.init({"builder": "mlp", "hidden": [32],
                          "num_outputs": 4}, input_shape=(8,), seed=0)
    model = NNModel(model=fn, input_col="features", batch_size=32,
                    tensor_parallel=2)
    srv = ServingServer(model, max_batch_size=8, max_latency_ms=2.0)
    srv.warmup({"features": [0.0] * 8})
    srv.start()
    rng = np.random.default_rng(0)
    try:
        base = f"http://{srv.host}:{srv.port}"
        rec0 = srv.n_recompiles
        for _ in range(n_requests):
            payload = json.dumps(
                {"features": [float(v) for v in rng.normal(size=8)]}
            ).encode()
            req = urllib.request.Request(
                base + "/predict", data=payload,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10).read()
        stats = json.loads(urllib.request.urlopen(
            base + "/stats", timeout=10).read())
        placement = stats.get("placement") or {}
        return {"post_warmup_recompiles": srv.n_recompiles - rec0,
                "placement_mode": placement.get("mode"),
                "mesh": placement.get("mesh"),
                "n_requests": n_requests,
                "ok": (srv.n_recompiles == rec0
                       and placement.get("mode") == "tensor_parallel")}
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# pipeline-parallel serving (multihost_pipeline_v1)
# ---------------------------------------------------------------------------


def pipeline_check(rows: int = 512, repeats: int = 3,
                   hidden=(256, 256, 256, 256)) -> dict:
    """Pipeline-parallel serving A/B — the ``multihost_pipeline_v1``
    evidence.

    A deep MLP is partitioned into 2 pipeline stages over 2 device
    slices (``NNModel(pipeline_parallel=2)``); the baseline serves the
    SAME model on a single stage's devices (the pinned single-device
    scope — exactly one slice's hardware when the harness runs with 2
    devices). Gates: >= 2 stages actually placed, zero post-warmup
    recompiles through a live ServingServer, bubble fraction measured
    and reported, and >= 1.25x rows/s over the single-stage baseline —
    with an explicit ``speedup_justification`` when the CPU sandbox
    cannot express inter-stage overlap (virtual devices share cores)."""
    import urllib.request
    import numpy as np
    import jax
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.function import NNFunction
    from mmlspark_tpu.models.nn import NNModel
    from mmlspark_tpu.parallel.topology import single_device_scope
    from mmlspark_tpu.serving.server import ServingServer

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"skipped": "pipeline parallelism needs >= 2 devices",
                "ok": True}
    pp = 2
    fn = NNFunction.init({"builder": "mlp", "hidden": list(hidden),
                          "num_outputs": 8}, input_shape=(64,), seed=0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(rows, 64)).astype(np.float32)
    df = DataFrame({"features": x})

    model = NNModel(model=fn, input_col="features",
                    pipeline_parallel=pp, pipeline_microbatches=4)
    ref = NNModel(model=fn, input_col="features")

    # parity first: the staged forward must equal the fused one
    out_pp = model.transform(df)["scores"]
    with single_device_scope():
        out_ref = ref.transform(df)["scores"]
    parity = float(np.abs(out_pp - out_ref).max())

    def best_rows_per_s(run):
        run()                                     # warm
        best = 0.0
        for _ in range(repeats):
            t0 = time.perf_counter()
            run()
            best = max(best, rows / (time.perf_counter() - t0))
        return best

    pp_rps = best_rows_per_s(lambda: model.transform(df))

    def base_run():
        with single_device_scope():
            ref.transform(df)
    base_rps = best_rows_per_s(base_run)
    speedup = pp_rps / max(base_rps, 1e-9)

    report = model.pipeline_report() or {}

    # zero post-warmup recompiles through a LIVE pipelined server,
    # with the /stats pipeline block as evidence
    srv = ServingServer(model, max_batch_size=16, max_latency_ms=2.0)
    srv.warmup({"features": [0.0] * 64})
    srv.start()
    try:
        base = f"http://{srv.host}:{srv.port}"
        rec0 = srv.n_recompiles
        for _ in range(24):
            payload = json.dumps(
                {"features": [float(v) for v in rng.normal(size=64)]}
            ).encode()
            req = urllib.request.Request(
                base + "/predict", data=payload,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10).read()
        stats = json.loads(urllib.request.urlopen(
            base + "/stats", timeout=10).read())
        live_pipe = stats.get("pipeline_parallel") or {}
        recompiles = srv.n_recompiles - rec0
    finally:
        srv.stop()

    on_cpu = jax.default_backend() == "cpu"
    speedup_ok = speedup >= 1.25
    out = {
        "n_stages": report.get("n_stages"),
        "stages": report.get("stages"),
        "bubble_ratio": report.get("bubble_ratio"),
        "parity_max_diff": parity,
        "pipeline_rows_per_s": round(pp_rps, 1),
        "single_stage_rows_per_s": round(base_rps, 1),
        "speedup_vs_single_stage": round(speedup, 3),
        "post_warmup_recompiles": int(recompiles),
        "live_stats_pipeline_block": bool(live_pipe.get("n_stages")),
        "live_bubble_ratio": live_pipe.get("bubble_ratio"),
    }
    if not speedup_ok and on_cpu:
        out["speedup_justification"] = (
            "CPU sandbox: virtual devices share one host's cores, so "
            "inter-stage overlap may not express as wall-clock "
            f"speedup (measured {speedup:.2f}x); the gate rides "
            "parity + staged placement + zero recompiles + measured "
            "bubble. Real-chip numbers land in MULTICHIP_r0*.json.")
    out["ok"] = bool(
        (report.get("n_stages") or 0) >= 2
        and parity < 1e-5
        and recompiles == 0
        and report.get("bubble_ratio") is not None
        and live_pipe.get("n_stages")
        and (speedup_ok or "speedup_justification" in out))
    return out


# ---------------------------------------------------------------------------
# 2-process DCN drill (multiprocess_dcn_v1 — subprocess, opt-in)
# ---------------------------------------------------------------------------


def dcn_drill(timeout: float = 300.0, smoke: bool = True) -> dict:
    """Spawn tools/launch_multiprocess.py: the REAL 2-process drill
    (gloo cross-process psum, fit parity, pipe-stage split, 2-process
    cooperative checkpoint save -> 1-process restore). Subprocess-
    isolated — the drill owns its jax runtimes — with the per-phase
    timeout degrading to a failed metric line, never a hung bench."""
    import subprocess
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "launch_multiprocess.py")
    cmd = [sys.executable, script, "--json",
           "--timeout", str(int(timeout))]
    if smoke:
        cmd.append("--smoke")
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout * 3)
    except subprocess.TimeoutExpired as e:
        return {"passed": False,
                "error": f"dcn drill timed out after {e.timeout}s"}
    try:
        return json.loads(p.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"passed": False, "rc": p.returncode,
                "error": (p.stdout + p.stderr)[-1200:]}


# ---------------------------------------------------------------------------
# sharded-checkpoint topology drill
# ---------------------------------------------------------------------------


def checkpoint_topology_drill() -> dict:
    """Save on 2x2, restore on 4x1 and 1x1; digests strict-verified."""
    import shutil
    import tempfile
    import numpy as np
    import jax
    from mmlspark_tpu.io import checkpoint as ckpt
    from mmlspark_tpu.parallel import dist

    rng = np.random.default_rng(7)
    tree = {"w": rng.normal(size=(64, 32)).astype(np.float32),
            "b": rng.normal(size=(32,)).astype(np.float32)}
    n = len(jax.devices())
    sharded = dist.shard_state(tree, dist.train_mesh(_tp_mesh_shape()))
    path = tempfile.mkdtemp(prefix="ckpt_topo_")
    try:
        mngr = ckpt.manager(path)
        mngr.save(1, sharded)
        ok_digest, _ = ckpt.verify_digest(mngr._step_dir(1), strict=True)
        results = {"digest_verified": bool(ok_digest)}
        shapes = [("1x1", {"data": 1})]
        if n >= 4:
            shapes.insert(0, ("4x1", {"data": 4}))
        elif n >= 2:
            shapes.insert(0, ("2x1", {"data": 2}))
        for label, shape in shapes:
            mesh = dist.train_mesh(shape)
            r = mngr.restore(1, tree,
                             shardings=dist.state_shardings(tree, mesh),
                             strict_digest=True)
            same = all(
                np.array_equal(np.asarray(a), b)
                for a, b in zip(jax.tree.leaves(r), jax.tree.leaves(tree)))
            results[f"restore_{label}_exact"] = bool(same)
        results["ok"] = all(v for v in results.values())
        return results
    finally:
        shutil.rmtree(path, ignore_errors=True)


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def _run_phase(name: str, fn, timeout_s: float) -> dict:
    """Run one in-process phase under a watchdog: a hung phase (the
    XLA:CPU collective-rendezvous deadlock class) degrades to a failed
    metric line instead of hanging the whole bench past its caller's
    budget. The worker thread is daemonized — it cannot be killed, but
    the bench reports and moves on (and the process exit reaps it)."""
    import threading
    box: dict = {}

    def work():
        try:
            box["result"] = fn()
        except Exception as e:  # noqa: BLE001 — failed phase = failed line
            box["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=work, daemon=True, name=f"phase-{name}")
    t0 = time.time()
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return {"ok": False, "passed": False,
                "error": f"phase {name!r} timed out after {timeout_s}s "
                         f"(thread abandoned)"}
    if "error" in box:
        return {"ok": False, "passed": False, "error": box["error"],
                "elapsed_s": round(time.time() - t0, 1)}
    return box["result"]


def run_all(counts=(1, 2, 4, 8), quick: bool = False,
            phase_timeout: float = 300.0, with_dcn: bool = False) -> dict:
    parity = _run_phase(
        "parity", lambda: parity_check(steps_epochs=3 if quick else 5),
        phase_timeout)
    curve = _run_phase(
        "curve", lambda: scaling_curve(counts=counts,
                                       n_long=20 if quick else 40,
                                       repeats=2 if quick else 3),
        phase_timeout)
    if isinstance(curve, dict):          # timed out / raised
        curve_err, curve = curve, []
    else:
        curve_err = None
    serving = _run_phase(
        "serving",
        lambda: serving_recompile_check(n_requests=16 if quick else 32),
        phase_timeout)
    ckpt = _run_phase("checkpoint", checkpoint_topology_drill,
                      phase_timeout)
    by_n = {c["devices"]: c["steps_per_s"] for c in curve}
    speedup_4x = ((by_n[4] / by_n[1])
                  if (4 in by_n and by_n.get(1)) else None)
    import jax
    on_cpu = jax.default_backend() == "cpu"
    speedup_ok = speedup_4x is not None and speedup_4x >= 1.5
    out = {
        "parity": parity,
        "curve": curve,
        "speedup_4x_vs_1": (round(speedup_4x, 3)
                            if speedup_4x is not None else None),
        "serving": serving,
        "checkpoint": ckpt,
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
    }
    if curve_err is not None:
        out["curve_error"] = curve_err
    if with_dcn:
        # the REAL multi-process story: opt-in (subprocess-heavy), a
        # smoke-mode sub-result so multihost_scaling_v1 carries DCN
        # evidence without blowing the tier-1/bench budget
        # capped well below the caller's outer budget: the drill's
        # graceful phase-group timeouts must all fire (failed metric
        # line) before any outer kill could orphan the gloo workers
        out["dcn"] = dcn_drill(timeout=min(phase_timeout, 150.0),
                               smoke=True)
    if not speedup_ok:
        # the acceptance contract: when the environment can't express
        # (or reach) the 1.5x target, the measured number is REPORTED
        # with an explicit justification instead of crashing or
        # silently gating — the gate then rides parity +
        # zero-recompile + checkpoint topology
        if speedup_4x is None:
            why = (f"host has {len(jax.devices())} device(s): the "
                   f"4-vs-1 point cannot be measured; the curve covers "
                   f"what exists")
        elif on_cpu:
            why = ("CPU simulation: virtual devices share one host's "
                   "cores and memory bandwidth, so partitioned-matmul "
                   "scaling saturates early. Real-chip numbers land "
                   "in MULTICHIP_r0*.json.")
        else:
            why = (f"measured {speedup_4x:.2f}x at 4 devices — below "
                   f"the 1.5x target for this config on this "
                   f"hardware; reported explicitly per the "
                   f"acceptance contract")
        out["speedup_justification"] = why
    out["passed"] = bool(parity.get("ok") and serving.get("ok")
                         and ckpt.get("ok") and curve
                         and (speedup_ok
                              or "speedup_justification" in out)
                         and (not with_dcn
                              or out["dcn"].get("passed")))
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="quick CI gate: asserts, nonzero exit on violation")
    ap.add_argument("--json", action="store_true",
                    help="print the evidence JSON only")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--phase", default="all",
                    choices=("all", "pipeline", "dcn"),
                    help="all = the multihost_scaling_v1 suite; "
                         "pipeline = the multihost_pipeline_v1 check "
                         "alone; dcn = the 2-process drill alone")
    ap.add_argument("--dcn", action="store_true",
                    help="include the 2-process DCN drill sub-result "
                         "in the full suite")
    ap.add_argument("--phase-timeout", type=float, default=300.0,
                    help="per-phase watchdog: a hung phase becomes a "
                         "failed metric line, not a hung bench")
    args = ap.parse_args()

    if args.phase == "dcn":
        out = dcn_drill(timeout=args.phase_timeout, smoke=args.smoke)
        print(json.dumps(out, indent=None if args.json else 2))
        sys.exit(0 if out.get("passed") else 1)

    _ensure_devices(2 if args.phase == "pipeline" else args.devices)
    if args.phase == "pipeline":
        out = _run_phase(
            "pipeline",
            lambda: pipeline_check(rows=256 if args.smoke else 512,
                                   repeats=2 if args.smoke else 3),
            args.phase_timeout)
        out["passed"] = bool(out.get("ok"))
        print(json.dumps(out, indent=None if args.json else 2))
        sys.exit(0 if out["passed"] else 1)

    counts = tuple(n for n in (1, 2, 4, 8) if n <= args.devices)
    out = run_all(counts=counts, quick=args.smoke,
                  phase_timeout=args.phase_timeout, with_dcn=args.dcn)
    print(json.dumps(out, indent=None if args.json else 2))
    if not out["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
