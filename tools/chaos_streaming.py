"""Chaos-drive the retrain->redeploy loop: a live fleet serves
idempotent traffic, its own committed request/reply rows journal into
the traffic capture, a ``fit_stream`` query retrains the model from
them, and a ``RetrainLoop`` pushes the resulting digest-manifested
checkpoint through the coordinator's canary rollout — while one worker
is SIGKILLed in the middle of the loop.

The multi-process companion to ``tests/test_streaming_engine.py``
(which pins the same loop in-process): real OS worker processes (the
``ServingServer`` the k8s pods run, each with its own
``TrafficCapture`` directory under one shared parent the driver's
``TrafficLogSource`` merges), a real coordinator, and a
``ServingClient`` pushing traffic with labels throughout.

Pass (exit 0) iff:
  * the rollout the loop pushed ends ``completed`` — the survivors
    finish the flip despite the kill;
  * ``GET /fleet`` reports ONE coherent (retrained) version across the
    responding workers;
  * ZERO client requests were dropped or answered malformed at any
    point (zero downtime, zero wrong replies);
  * the trainer's exactly-once counters are clean: every micro-batch
    id trained at most once (no replay double-trained).

    python tools/chaos_streaming.py                # defaults: 3 workers
    python tools/chaos_streaming.py --workers 4 --seed 7
"""

import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from chaos_serving import spawn_worker  # noqa: E402

STREAM_WORKER_SCRIPT = """
import sys, time
from mmlspark_tpu.serving.server import ServingServer, ServingCoordinator
from mmlspark_tpu.serving.capture import TrafficCapture
from mmlspark_tpu.core.stage import PipelineStage

# argv: coord_url, model_dir, capture_dir, journal
model = PipelineStage.load(sys.argv[2])
srv = ServingServer(model, max_latency_ms=1, max_batch_size=4,
                    journal_path=sys.argv[4], model_version="v1",
                    capture=TrafficCapture(sys.argv[3]),
                    slow_trace_ms=None)
srv.warmup({"x": [0.0, 0.0], "label": 0.0})
srv.start()
ServingCoordinator.register_worker(sys.argv[1], srv.host, srv.port)
print(srv.port, flush=True)
while True:
    time.sleep(1)
"""


def retrain_loop_drill(tmp: str, seed: int, n_workers: int = 3) -> dict:
    import numpy as np
    import requests

    from mmlspark_tpu.models.function import NNFunction
    from mmlspark_tpu.models.nn import NNModel
    from mmlspark_tpu.models.trainer import NNLearner
    from mmlspark_tpu.serving.server import (
        ServingClient, ServingCoordinator)
    from mmlspark_tpu.streaming import RetrainLoop, TrafficLogSource

    # v1: an untrained tiny MLP, digest-manifested
    v1_dir = os.path.join(tmp, "model_v1")
    fn = NNFunction.init({"builder": "mlp", "hidden": [4],
                          "num_outputs": 1}, (2,), seed=seed)
    NNModel(model=fn, input_col="x", output_col="scores").save(v1_dir)
    capdir = os.path.join(tmp, "capture")
    warm = {"x": [0.0, 0.0], "label": 0.0}

    coord = ServingCoordinator().start()
    coord_url = f"http://{coord.host}:{coord.port}"
    workers = [
        spawn_worker(coord_url, os.path.join(tmp, f"j{i}.jsonl"),
                     STREAM_WORKER_SCRIPT, v1_dir,
                     os.path.join(capdir, f"w{i}"))
        for i in range(n_workers)]

    stats = {"n_ok": 0, "n_wrong": 0, "dropped": [],
             "killed_during": None}
    stop = threading.Event()
    client = ServingClient(coord_url, timeout=10)
    rng = np.random.default_rng(seed)

    def traffic() -> None:
        i = 0
        while not stop.is_set():
            i += 1
            x = rng.normal(size=2)
            rid = f"stream-{seed}-{i}"
            try:
                out = client.predict(
                    {"x": x.tolist(), "label": float(x.sum())},
                    request_id=rid)
            except Exception as e:  # noqa: BLE001 — a dropped request
                stats["dropped"].append({"rid": rid, "error": str(e)})
                continue
            # versions flip mid-traffic, so scores change — a correct
            # reply is a well-formed scores vector, from ANY version
            if isinstance(out.get("scores"), list) and out["scores"]:
                stats["n_ok"] += 1
            else:
                stats["n_wrong"] += 1

    t = threading.Thread(target=traffic)
    t.start()
    final = fleet = None
    loop = fit = None
    try:
        # -- stream the fleet's own traffic into the trainer
        learner = NNLearner(
            arch={"builder": "mlp", "hidden": [4], "num_outputs": 1},
            features_col="x", label_col="label", loss="squared_error",
            optimizer="adam", learning_rate=0.02, batch_size=16,
            checkpoint_dir=os.path.join(tmp, "train"))
        fit = learner.fit_stream(
            TrafficLogSource(capdir),
            export_dir=os.path.join(tmp, "exports"),
            export_every_batches=2,
            checkpoint_dir=os.path.join(tmp, "wal"),
            max_batch_rows=32, trigger_interval_s=0.05)
        fit.query.start()
        deadline = time.perf_counter() + 90
        while time.perf_counter() < deadline and not fit.exports:
            time.sleep(0.1)
        if not fit.exports:
            raise RuntimeError("fit_stream produced no export in 90s "
                               f"(query: {fit.query.status()})")

        # -- the loop pushes it through the canary; canary_min_requests
        # sized so the kill lands mid-rollout
        loop = RetrainLoop(
            os.path.join(tmp, "exports"), coord_url,
            warmup_payload=warm, poll_interval_s=0.05,
            rollout={"canary": True, "canary_min_requests": 120,
                     "canary_window_s": 10.0, "stage_timeout_s": 60.0,
                     "poll_interval_s": 0.05}).start()

        deadline = time.perf_counter() + 30
        state = "pending"
        while time.perf_counter() < deadline:
            st = requests.get(coord_url + "/rollout", timeout=10).json()
            state = st.get("state", "idle")
            if state in ("canary", "flipping", "completed",
                         "rolled_back", "failed"):
                break
            time.sleep(0.05)
        # SIGKILL a NON-canary worker (the orchestrator canaries the
        # first registered) in the middle of the loop's rollout
        stats["killed_during"] = state
        os.kill(workers[-1].pid, signal.SIGKILL)
        workers[-1].wait()

        deadline = time.perf_counter() + 90
        while time.perf_counter() < deadline \
                and loop.n_completed == 0 and loop.n_failed == 0 \
                and loop.n_rolled_back == 0:
            time.sleep(0.1)
        loop.stop()
        fit.query.stop()
        # the loop may have pushed a newer export before stop() landed:
        # wait for the coordinator's in-flight rollout to reach a
        # terminal state before judging fleet coherence
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:
            st = requests.get(coord_url + "/rollout", timeout=10).json()
            if st.get("state") in ("idle", "completed", "rolled_back",
                                   "failed"):
                break
            time.sleep(0.1)
        final = loop.status()
        if st.get("state") == "completed":
            final["history"].append(
                {"version": st["version"], "state": "completed"})
        fleet = requests.get(coord_url + "/fleet", timeout=10).json()
        trainer = fit.status()["trainer"]
    finally:
        stop.set()
        t.join()
        if loop is not None:
            loop.stop()
        if fit is not None:
            fit.query.stop()
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        coord.stop()

    completed = [h["version"] for h in (final["history"] if final else [])
                 if h.get("state") == "completed"]
    # a trailing rolled-back push leaves the fleet on the last
    # COMPLETED version — that is the coherence target
    new_version = completed[-1] if completed else None
    # exactly-once evidence: batch ids trained once each — the count of
    # trained batches equals the high-water id minus replays skipped
    exactly_once = (trainer["n_batches_trained"]
                    + trainer["n_replays_skipped"]
                    <= trainer["last_trained_batch"]
                    and trainer["n_batches_trained"] > 0) if final \
        else False
    ok = (final is not None
          and new_version is not None
          and stats["killed_during"] in ("staging", "shadow", "canary",
                                         "flipping")
          and fleet is not None
          and fleet.get("model_versions") == [new_version]
          and fleet.get("version_coherent")
          and fleet.get("n_responding") == n_workers - 1
          and stats["n_wrong"] == 0 and not stats["dropped"]
          and stats["n_ok"] > 0
          and exactly_once)
    return {
        "what": "retrain->redeploy loop with a worker SIGKILLed "
                "mid-loop; survivors must serve the retrained version",
        "n_workers": n_workers,
        "killed_during": stats["killed_during"],
        "loop": {"n_pushed": final["n_pushed"] if final else 0,
                 "history": final["history"][-3:] if final else []},
        "fleet_versions": fleet.get("model_versions") if fleet else None,
        "version_coherent": fleet.get("version_coherent")
        if fleet else None,
        "n_responding": fleet.get("n_responding") if fleet else None,
        "trainer": trainer if final else None,
        "exactly_once": exactly_once,
        "traffic": {"n_ok": stats["n_ok"], "n_wrong": stats["n_wrong"],
                    "n_dropped": len(stats["dropped"]),
                    "dropped": stats["dropped"][:5]},
        "ok": ok,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    with tempfile.TemporaryDirectory(prefix="chaos_streaming_") as tmp:
        report = retrain_loop_drill(tmp, args.seed,
                                    n_workers=args.workers)
    print(json.dumps(report, indent=2, default=str))
    print(f"\n[chaos_streaming] {'PASS' if report['ok'] else 'FAIL'}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
