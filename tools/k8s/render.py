"""Parameterize the serving-fleet manifests (the helm-values analogue).

The reference ships its serving layer as a parameterized helm chart
(`/root/reference/tools/helm/spark-serving/values.yaml`); this is the
same capability without a helm dependency: the committed manifests under
``tools/k8s/`` ARE the rendered defaults, and this tool re-renders them
with overrides — ``helm template --set`` semantics over plain YAML.

    python tools/k8s/render.py \
        --set replicas=5 --set image=gcr.io/me/mmlspark-tpu:v2 \
        --set model_uri=gs://me/models/served \
        --set journal_pvc=serving-journal > fleet.yaml
    kubectl apply -f fleet.yaml

Supported values (anything else: edit the YAML, it is the source of
truth): ``replicas`` (worker count), ``image`` (both deployments),
``model_uri``, ``coordinator_url``, ``max_latency_ms``,
``journal_size``, ``stale_after``, ``journal_pvc`` (an existing
PersistentVolumeClaim name: mounts it at ``/journal`` and points each
worker's durable reply journal at a per-pod file there —
exactly-once replies then survive pod crash-restarts), and any worker
env var via ``env.NAME=value`` (including a raw ``env.JOURNAL_PATH``
if you manage the volume yourself). The listen port is deliberately
NOT a value — it is wired through containerPort, the named-port
probes, the Service, and COORDINATOR_URL, so changing it is a YAML
edit, not an override.
"""

import argparse
import os
import sys

import yaml

HERE = os.path.dirname(os.path.abspath(__file__))
MANIFESTS = ("serving-coordinator.yaml", "serving-workers.yaml")


def _containers(doc):
    if doc.get("kind") != "Deployment":
        return []
    return doc["spec"]["template"]["spec"]["containers"]


def _set_env(container, name: str, value: str) -> None:
    env = container.setdefault("env", [])
    for e in env:
        if e.get("name") == name:
            e.clear()
            e.update({"name": name, "value": str(value)})
            return
    env.append({"name": name, "value": str(value)})


def _role(doc) -> str:
    return (doc.get("metadata", {}).get("labels", {}) or {}).get("role", "")


def render(values):
    docs = []
    for fname in MANIFESTS:
        with open(os.path.join(HERE, fname)) as f:
            docs.extend(d for d in yaml.safe_load_all(f) if d)

    env_map = {"model_uri": "MODEL_URI", "coordinator_url": "COORDINATOR_URL",
               "max_latency_ms": "MAX_LATENCY_MS",
               "journal_size": "JOURNAL_SIZE"}
    for doc in docs:
        role = _role(doc)
        for c in _containers(doc):
            if "image" in values:
                c["image"] = values["image"]
            if role == "worker":
                for key, env_name in env_map.items():
                    if key in values:
                        _set_env(c, env_name, values[key])
                if "journal_pvc" in values:
                    # durable journal on a mounted PVC, one file per pod
                    # (replicas must not clobber a shared journal)
                    c.setdefault("volumeMounts", []).append(
                        {"name": "journal", "mountPath": "/journal"})
                    env = c.setdefault("env", [])
                    if not any(e.get("name") == "POD_NAME" for e in env):
                        env.append({"name": "POD_NAME", "valueFrom": {
                            "fieldRef": {"fieldPath": "metadata.name"}}})
                    _set_env(c, "JOURNAL_PATH",
                             "/journal/$(POD_NAME).jsonl")
                for name, v in values.get("env", {}).items():
                    _set_env(c, name, v)
            if role == "coordinator" and "stale_after" in values:
                _set_env(c, "STALE_AFTER", values["stale_after"])
        if role == "worker" and doc.get("kind") == "Deployment":
            if "replicas" in values:
                doc["spec"]["replicas"] = int(values["replicas"])
            if "journal_pvc" in values:
                doc["spec"]["template"]["spec"].setdefault(
                    "volumes", []).append(
                    {"name": "journal", "persistentVolumeClaim":
                        {"claimName": values["journal_pvc"]}})
    return docs


SUPPORTED_KEYS = frozenset({
    "replicas", "image", "model_uri", "coordinator_url", "max_latency_ms",
    "journal_size", "journal_pvc", "stale_after"})


def parse_sets(pairs):
    values = {"env": {}}
    for p in pairs:
        key, sep, val = p.partition("=")
        if not sep:
            raise SystemExit(f"--set needs key=value, got {p!r}")
        if key.startswith("env.") and len(key) > 4:
            values["env"][key[4:]] = val
        elif key in SUPPORTED_KEYS:
            values[key] = val
        else:
            # a typo must not silently deploy the defaults
            raise SystemExit(
                f"unknown --set key {key!r}; supported: "
                f"{', '.join(sorted(SUPPORTED_KEYS))}, env.NAME")
    return values


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--set", action="append", default=[], metavar="K=V",
                    help="override a value (repeatable); env.NAME=V sets "
                         "a worker env var")
    args = ap.parse_args()
    docs = render(parse_sets(args.set))
    yaml.safe_dump_all(docs, sys.stdout, sort_keys=False,
                       default_flow_style=False)


if __name__ == "__main__":
    main()
