"""Capacity vs dense MoE dispatch: executed FLOPs as the expert count
grows, from XLA's own cost analysis on the 8-device dryrun mesh.

Dense dispatch multiplies every token through every LOCAL expert (FLOPs
scale with E); capacity dispatch routes bounded per-expert queues
through two all_to_alls (FLOPs scale with capacity_factor x top_k).
This records the compiled train step's per-device FLOPs for both modes
at growing E — the measured form of the scaling claim the cost-analysis
test pins (`tests/test_transformer.py`), and the reason the production
config runs capacity dispatch.

    python tools/bench_moe_dispatch.py

Writes ``docs/artifacts/moe_dispatch.json``.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mmlspark_tpu.parallel.topology import use_cpu_devices  # noqa: E402

use_cpu_devices(8)


def step_flops(cfg, mesh) -> float:
    import jax
    import numpy as np
    from mmlspark_tpu.models import transformer as T

    params = T.shard_params(T.init_params(cfg, seed=0), cfg, mesh)
    velocity = jax.tree.map(lambda p: p * 0.0, params)
    rng = np.random.default_rng(0)
    tokens, labels, mask = T.make_batch(rng, cfg, 8, 128)
    step = T.build_spmd_train_step(cfg, mesh, donate=False)
    cost = step.lower(params, velocity, tokens, labels,
                      mask).compile().cost_analysis() or {}
    return float(cost.get("flops", 0.0))


def main() -> None:
    from mmlspark_tpu.models import transformer as T
    from mmlspark_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec.from_dict({"expert": 8}))
    base = dict(vocab=256, d_model=64, n_heads=2, d_head=32, d_ff=256,
                n_stages=1, layers_per_stage=2, moe_top_k=2)
    out = {"mesh": "expert=8 (virtual CPU dryrun mesh)",
           "batch": 8, "seq": 128, "rows": []}
    for E in (8, 16, 32):
        dense = step_flops(
            T.TransformerConfig(n_experts=E, **base), mesh)
        cap = step_flops(
            T.TransformerConfig(n_experts=E, moe_capacity_factor=1.25,
                                **base), mesh)
        out["rows"].append({"n_experts": E,
                            "dense_gflops_per_dev": round(dense / 1e9, 3),
                            "capacity_gflops_per_dev": round(cap / 1e9, 3),
                            "capacity_vs_dense": round(cap / dense, 3)})
    r0, r2 = out["rows"][0], out["rows"][-1]
    out["summary"] = (
        "dense grows {:.2f}x from E=8 to E=32; capacity grows {:.2f}x "
        "(factor*k bounded)".format(
            r2["dense_gflops_per_dev"] / r0["dense_gflops_per_dev"],
            r2["capacity_gflops_per_dev"] / r0["capacity_gflops_per_dev"]))

    path = os.path.join(REPO, "docs", "artifacts", "moe_dispatch.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
