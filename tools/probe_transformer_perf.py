"""Where does the transformer train step spend its time? (dev-chip probe)

Times single-device step VARIANTS with the dependent-chain slope method
(host timing of dispatched work lies on the tunneled chip — see
bench.py:_chain_slope_seconds) to attribute ms/step to: attention
softmax traffic, the 32k-vocab CE, the optimizer update, and dispatch.

    python tools/probe_transformer_perf.py [variant ...]

Each variant prints one JSON line {variant, ms_per_step, mfu?}.
"""

import functools
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax                                    # noqa: E402
import jax.numpy as jnp                       # noqa: E402

from mmlspark_tpu.models import transformer as T          # noqa: E402
from mmlspark_tpu.parallel.ring_attention import dense_attention  # noqa: E402

CFG = T.TransformerConfig(vocab=32768, d_model=512, n_heads=8,
                          d_head=64, d_ff=2048, n_stages=1,
                          layers_per_stage=8, dtype="bfloat16")
AX = T._Axes(None, None, None, None, None)
PEAK = 197e12


def flops_per_step(cfg, batch, seq):
    L = cfg.n_stages * cfg.layers_per_stage
    d_attn = cfg.n_heads * cfg.d_head
    n_matmul = (cfg.d_model * cfg.vocab
                + L * (4 * cfg.d_model * d_attn + 2 * cfg.d_model * cfg.d_ff))
    return 6.0 * n_matmul * batch * seq + 12.0 * L * batch * seq * seq * d_attn


def chain_slope(run_chain, n_short=2, n_long=10, repeats=3):
    times = {}
    for n in (n_short, n_long):
        run_chain(n)
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            run_chain(n)
            best = min(best, time.perf_counter() - t0)
        times[n] = best
    slope = (times[n_long] - times[n_short]) / (n_long - n_short)
    return slope if slope > 0 else times[n_long] / n_long


def body_forward(params, tokens, cfg, attn_mode):
    """Embed + blocks (+ optionally attention) + final norm -> h."""
    x = params["embed"][tokens]
    pos = jnp.arange(tokens.shape[1])
    dt = T._compute_dtype(cfg)
    for bp_all in params["blocks"]:
        bp = {k: v[0] for k, v in bp_all.items()}
        if attn_mode != "none":
            h = T._rmsnorm(x, bp["ln1"]).astype(dt)
            q = jnp.einsum("bsd,dhk->bshk", h, bp["wq"].astype(dt)
                           ).astype(jnp.float32)
            k = jnp.einsum("bsd,dhk->bshk", h, bp["wk"].astype(dt)
                           ).astype(jnp.float32)
            v = jnp.einsum("bsd,dhk->bshk", h, bp["wv"].astype(dt)
                           ).astype(jnp.float32)
            q, k = T._rope(q, pos), T._rope(k, pos)
            if attn_mode == "folded":
                from mmlspark_tpu.parallel.pallas_attention import (
                    flash_attention_folded)
                a = flash_attention_folded(q.astype(dt), k.astype(dt),
                                           v.astype(dt), True)
            elif attn_mode in ("flash_xla", "flash_pallas"):
                from mmlspark_tpu.parallel.pallas_attention import (
                    flash_attention)
                a = flash_attention(q.astype(dt), k.astype(dt), v.astype(dt),
                                    True, None, False,
                                    attn_mode.split("_")[1])
            elif attn_mode == "bf16p":
                dh = q.shape[-1]
                s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(dt), k.astype(dt),
                               preferred_element_type=jnp.float32) * dh ** -0.5
                sq = q.shape[1]
                mask = jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :]
                s = jnp.where(mask[None, None], s, -1e30)
                p = jax.nn.softmax(s, axis=-1).astype(dt)   # bf16 stored p
                a = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(dt),
                               preferred_element_type=jnp.float32)
            else:
                a = dense_attention(q, k, v, causal=True, compute_dtype=dt)
            o = jnp.einsum("bshk,hkd->bsd", a.astype(dt), bp["wo"].astype(dt)
                           ).astype(jnp.float32)
            x = x + o
        x = x + T._mlp(bp, x, AX, cfg)
    return T._rmsnorm(x, params["final_norm"])


def ce_loss(params, h, labels, mask, cfg, mode):
    dt = T._compute_dtype(cfg)
    if mode == "none":
        return jnp.sum(h * h) * 1e-6
    if mode.startswith("chunked"):
        C = int(mode.split(":")[1]) if ":" in mode else 128
        b, s, d = h.shape
        n = s // C
        W = params["head"].astype(dt)
        hs = jnp.swapaxes(h.reshape(b, n, C, d), 0, 1)
        ls = jnp.swapaxes(labels.reshape(b, n, C), 0, 1)
        ms = jnp.swapaxes(mask.reshape(b, n, C), 0, 1)

        @jax.checkpoint
        def body(carry, args):
            hc, lc, mc = args
            logits = jnp.einsum("bcd,dv->bcv", hc.astype(dt), W,
                                preferred_element_type=jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, lc[..., None],
                                       axis=-1)[..., 0]
            return carry + jnp.sum((lse - gold) * mc), None
        total, _ = jax.lax.scan(body, jnp.float32(0.0), (hs, ls, ms))
        return total / jnp.maximum(jnp.sum(mask), 1.0)
    logits = jnp.einsum("bsd,dv->bsv", h.astype(dt),
                        params["head"].astype(dt),
                        preferred_element_type=jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.sum((lse - gold) * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def make_step(cfg, attn_mode="dense", ce_mode="full", fwd_only=False,
              opt=True, lr=0.01, momentum=0.9):
    def loss_fn(params, tokens, labels, mask):
        h = body_forward(params, tokens, cfg, attn_mode)
        return ce_loss(params, h, labels, mask, cfg, ce_mode)

    if fwd_only:
        @jax.jit
        def step(params, velocity, tokens, labels, mask):
            return params, velocity, loss_fn(params, tokens, labels, mask)
        return step

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, velocity, tokens, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels,
                                                  mask)
        if opt:
            velocity = jax.tree.map(lambda v, g: momentum * v + g,
                                    velocity, grads)
            params = jax.tree.map(lambda p, v: p - lr * v, params, velocity)
        else:
            params = jax.tree.map(lambda p, g: p - lr * g * 0, params, grads)
        return params, velocity, loss
    return step


def run_variant(name, batch=8, seq=1024, **kw):
    seq = int(seq)
    params = T.init_params(CFG, seed=0)
    params = jax.device_put(params)
    velocity = jax.tree.map(lambda p: p * 0.0, params)
    rng = np.random.default_rng(0)
    tokens, labels, mask = T.make_batch(rng, CFG, batch, seq)
    step = make_step(CFG, **kw)
    state = {"p": params, "v": velocity}

    def run_chain(n):
        for _ in range(n):
            state["p"], state["v"], loss = step(state["p"], state["v"],
                                                tokens, labels, mask)
        float(loss)

    sec = chain_slope(run_chain)
    out = {"variant": name, "batch": batch, "ms_per_step": round(sec * 1e3, 2)}
    if kw.get("attn_mode") != "none" and kw.get("ce_mode") != "none" \
            and not kw.get("fwd_only"):
        mfu = flops_per_step(CFG, batch, seq) / sec / PEAK
        out["mfu"] = round(mfu, 4)
    print(json.dumps(out), flush=True)


VARIANTS = {
    "full": dict(),
    "bf16p": dict(attn_mode="bf16p"),
    "no_ce": dict(ce_mode="none"),
    "no_attn": dict(attn_mode="none"),
    "fwd_only": dict(fwd_only=True),
    "no_opt": dict(opt=False),
    "full_b16": dict(batch=16),
    "bf16p_b16": dict(attn_mode="bf16p", batch=16),
    "full_b32": dict(batch=32),
    "flash_xla": dict(attn_mode="flash_xla"),
    "flash_pallas": dict(attn_mode="flash_pallas"),
    "flash_pallas_b16": dict(attn_mode="flash_pallas", batch=16),
    "folded": dict(attn_mode="folded"),
    "folded_b16": dict(attn_mode="folded", batch=16),
    "folded_noopt": dict(attn_mode="folded", opt=False),
    "folded_s512": dict(attn_mode="folded", batch=16, seq=512),
    "full_s512": dict(batch=16, seq=512),
    "folded_s256": dict(attn_mode="folded", batch=32, seq=256),
    "full_s256": dict(batch=32, seq=256),
    "folded_noce": dict(attn_mode="folded", ce_mode="none"),
    "folded_ce128": dict(attn_mode="folded", ce_mode="chunked:128"),
    "folded_ce256": dict(attn_mode="folded", ce_mode="chunked:256"),
    "folded_ce512": dict(attn_mode="folded", ce_mode="chunked:512"),
    "folded_s4096_b2": dict(attn_mode="folded", batch=2, seq=4096),
    "full_s4096_b2": dict(batch=2, seq=4096),
    "flashxla_s4096_b2": dict(attn_mode="flash_xla", batch=2, seq=4096),
}


def main():
    names = sys.argv[1:] or list(VARIANTS)
    print(json.dumps({"devices": [str(d) for d in jax.devices()],
                      "backend": jax.default_backend()}), flush=True)
    for n in names:
        kw = dict(VARIANTS[n])
        batch = kw.pop("batch", 8)
        run_variant(n, batch=batch, **kw)


if __name__ == "__main__":
    main()
