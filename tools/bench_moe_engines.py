"""Scatter vs counting-sort capacity-dispatch engines: real-chip wall
time of dispatch+combine (fwd+bwd) as the expert count grows.

The FLOPs-side scaling story lives in ``bench_moe_dispatch.py`` (cost
analysis on the CPU dryrun mesh); this tool times the dispatch
MACHINERY itself on the actual chip at the production token shape —
the r4 verdict's "one-hot/scatter dispatch cost grows with E" item.
Total queue slots E*C are held constant (C = ceil(factor*Tk/E)), so any
growth is pure engine overhead, not capacity.

    python tools/bench_moe_engines.py      # needs the TPU chip

Appends an ``engine_wall_time`` section to
``docs/artifacts/moe_dispatch.json``.
"""

import functools
import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _engine(mode, h_rep, top, wf, E, C, dt):
    # both engines come FROM the transformer: the A/B times exactly the
    # dispatch code `_moe_capacity` runs, and cannot drift from it
    import mmlspark_tpu.models.transformer as TT
    if mode == "sort":
        return TT._sorted_capacity_queues(h_rep.astype(dt), top, wf,
                                          E, C, dt)
    return TT._scatter_capacity_queues(h_rep, top, wf, E, C, dt)


def time_engine(mode: str, E: int, Tk: int = 16384, d: int = 512,
                factor: float = 1.25) -> float:
    """ms per dispatch+combine fwd+bwd at constant total slots."""
    C = max(int(math.ceil(factor * Tk / E)), 1)
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(Tk, d)), dtype=jnp.float32)
    top = jnp.asarray(rng.integers(0, E, Tk), dtype=jnp.int32)
    wf = jnp.ones((Tk,), jnp.float32)

    def roundtrip(hh):
        disp, combine = _engine(mode, hh, top, wf, E, C, jnp.bfloat16)
        return jnp.sum(combine(disp.astype(jnp.float32)) ** 2)

    @functools.partial(jax.jit, static_argnames="n")
    def scan(hh, n):
        def body(c, _):
            l, g = jax.value_and_grad(roundtrip)(c)
            return c + 1e-9 * g, l
        _, ls = jax.lax.scan(body, hh, None, length=n)
        return ls

    def run(n):
        float(scan(h, n)[-1])

    run(2)
    # sub-ms per iteration: the chain must be long enough that the
    # long/short delta (~60 iterations) dwarfs the tunneled fetch jitter
    ts = {}
    for n in (4, 64):
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            run(n)
            best = min(best, time.perf_counter() - t0)
        ts[n] = best
    slope = (ts[64] - ts[4]) / 60 * 1000
    return slope if slope > 0 else ts[64] / 64 * 1000


def main() -> None:
    from mmlspark_tpu.core.environment import environment_info
    info = environment_info()
    # two interleaved rounds, min per cell: the tunneled chip's
    # host-side timing drifts by >1 ms between process phases, and the
    # min of interleaved rounds cancels that drift for both engines
    # equally
    cells = {(E, m): float("inf") for E in (8, 16, 32)
             for m in ("scatter", "sort")}
    for _ in range(2):
        for E in (8, 16, 32):
            for mode in ("scatter", "sort"):
                cells[(E, mode)] = min(cells[(E, mode)],
                                       time_engine(mode, E))
    rows = []
    for E in (8, 16, 32):
        row = {"n_experts": E,
               "scatter_ms": round(cells[(E, "scatter")], 3),
               "sort_ms": round(cells[(E, "sort")], 3)}
        rows.append(row)
        print(row, flush=True)
    speedups = [r["scatter_ms"] / r["sort_ms"] for r in rows]
    section = {
        "what": "dispatch+combine fwd+bwd wall time per layer, Tk=16384 "
                "x d=512, total slots E*C constant (factor 1.25)",
        "chip": info.get("device_kind"),
        "rows": rows,
        "summary": "counting-sort beats the scatter engine {:.1f}-{:.1f}x "
                   "across E=8..32 (no row scatter in either autodiff "
                   "direction)".format(min(speedups), max(speedups)),
    }
    path = os.path.join(REPO, "docs", "artifacts", "moe_dispatch.json")
    with open(path) as fh:
        art = json.load(fh)
    art["engine_wall_time"] = section
    with open(path, "w") as fh:
        json.dump(art, fh, indent=2)
    print(json.dumps(section))


if __name__ == "__main__":
    main()
