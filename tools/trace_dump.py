"""Fetch tail-captured traces from a serving worker (or a whole fleet
via its coordinator) and render them.

A worker retains every slow (over its ``slow_trace_ms`` route
threshold — adaptive by default, tracking the route's p95) or non-ok
(error/shed/deadline/timeout) trace in its flight-recorder store (see
docs/observability.md "Tracing"). This CLI lists that store,
pretty-prints one trace's span tree, or writes the Chrome
``trace_event`` JSON that ``chrome://tracing`` and
https://ui.perfetto.dev open directly:

    python tools/trace_dump.py http://worker:8000 --list
    python tools/trace_dump.py http://worker:8000 --list --slow
    python tools/trace_dump.py http://worker:8000 <trace-id>
    python tools/trace_dump.py http://worker:8000 <trace-id> -o t.json
    python tools/trace_dump.py http://worker:8000 --slowest -o t.json

With ``--fleet`` the URL names a ServingCoordinator instead: ``--list``
shows every worker's captures in one listing (worker-attributed,
slowest first, dead workers reported on stderr), and fetching a trace
returns the MERGED distributed tree — the client's failover schedule
with each worker's span tree stitched under its egress attempt
(``GET /fleet/traces`` / ``GET /fleet/trace/<id>``; the Perfetto
export renders each worker in its own lane):

    python tools/trace_dump.py --fleet http://coordinator:8000 --list
    python tools/trace_dump.py --fleet http://coordinator:8000 <trace-id>
    python tools/trace_dump.py --fleet http://coordinator:8000 --slowest -o t.json

``--alerts`` / ``--slo`` switch to the SLO engine instead of the
trace store (docs/observability.md "SLOs and alerting"): ``--alerts``
prints the compact alert view (state, violating window pair,
attribution), ``--slo`` the full burn-rate report per policy. Both
compose with ``--fleet`` (merged evaluation, per-worker blocks):

    python tools/trace_dump.py http://worker:8000 --alerts
    python tools/trace_dump.py http://worker:8000 --slo
    python tools/trace_dump.py --fleet http://coordinator:8000 --alerts

``--query`` / ``--range`` switch to the retrospective plane (the
embedded TSDB — docs/observability.md "The retrospective plane"):
``--query EXPR`` prints the instant result table, ``--range EXPR``
renders each returned series as an ANSI sparkline row (min/max/last
alongside). Both compose with ``--fleet`` (the coordinator fans the
expression out and merges the series under worker labels):

    python tools/trace_dump.py http://worker:8000 \\
        --query 'rate(serving_requests_total[60s])'
    python tools/trace_dump.py http://worker:8000 \\
        --range 'quantile(0.95, serving_dispatch_latency_ms[300s])' \\
        --window 600 --step 10
    python tools/trace_dump.py --fleet http://coordinator:8000 \\
        --range 'serving:decode_ttft_ms:p95'

``--incidents`` / ``--profile`` switch to the postmortem plane
(docs/observability.md "The postmortem plane"): ``--incidents`` lists
captured incident bundles (fleet-wide and worker-attributed with
``--fleet``), ``--fetch <id> -o dir`` downloads one bundle's artifacts
into a directory (verifying the manifest digests), and ``--profile``
renders a collapsed-stack top-table from the always-on sampling
profiler's ``GET /profile/cpu`` (``--baseline N`` switches to the
differential "which frames got hotter" table):

    python tools/trace_dump.py http://worker:8000 --incidents
    python tools/trace_dump.py --fleet http://coordinator:8000 --incidents
    python tools/trace_dump.py http://worker:8000 --incidents \\
        --fetch inc-... -o ./bundle
    python tools/trace_dump.py http://worker:8000 --profile --window 30
    python tools/trace_dump.py http://worker:8000 --profile --baseline 60

stdlib-only on the wire (urllib): runs anywhere the worker is
reachable, no client deps.
"""

from __future__ import annotations

import argparse
import json
import sys
from urllib.error import HTTPError
from urllib.parse import quote
from urllib.request import urlopen


def _get_json(url: str, timeout: float = 10.0):
    with urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _print_tree(node: dict, depth: int = 0) -> None:
    flag = "" if node["status"] == "ok" else f"  [{node['status']}]"
    worker = node.get("worker")
    wtag = f"  ({worker})" if worker else ""
    attrs = node.get("attrs") or {}
    extra = "".join(f" {k}={v}" for k, v in sorted(attrs.items())
                    if k != "route")
    print(f"{'  ' * depth}{node['name']:<{max(24 - 2 * depth, 1)}} "
          f"@{node['start_ms']:>9.3f}ms  {node['duration_ms']:>9.3f}ms"
          f"{extra}{wtag}{flag}")
    for child in sorted(node.get("children", []),
                        key=lambda c: c["start_ms"]):
        _print_tree(child, depth + 1)


def _print_listing(traces: list, fleet: bool) -> None:
    for t in traces:
        wcol = f" {t.get('worker', ''):<22}" if fleet else ""
        print(f"{t['trace_id']:<34}{wcol} {t['root']:<12} "
              f"{t.get('route', ''):<14} "
              f"{t['duration_ms']:>10.3f}ms  {t['reason']:<9} "
              f"spans={t['n_spans']}")
    if not traces:
        print("(no retained traces — nothing slow or failed yet)",
              file=sys.stderr)


def _fmt_window(w: dict) -> str:
    mark = "  << VIOLATED" if w.get("violated") else ""
    return (f"long {w['long_s']:>6.0f}s burn={w.get('burn_long', 0):>7.2f}"
            f"  short {w['short_s']:>5.0f}s "
            f"burn={w.get('burn_short', 0):>7.2f}"
            f"  (fires at {w['burn_threshold']}x){mark}")


def _print_alert(a: dict, depth: int = 0) -> None:
    pad = "  " * depth
    print(f"{pad}{a['policy']:<20} [{a['state']:<8}] "
          f"{a['kind']}  objective={a['objective']}")
    for w in a.get("windows") or []:
        print(f"{pad}  {_fmt_window(w)}")
    for row in a.get("attribution") or []:
        labels = ",".join(f"{k}={v}"
                          for k, v in sorted(row["labels"].items()))
        print(f"{pad}  burning: {labels}  bad={row['bad']:.0f}")


def _print_alerts_view(view: dict, depth: int = 0) -> None:
    pad = "  " * depth
    alerts = view.get("alerts") or []
    print(f"{pad}firing={view.get('firing', 0)}  "
          f"active_alerts={len(alerts)}")
    for a in alerts:
        _print_alert(a, depth)


def _print_slo_report(rep: dict, depth: int = 0) -> None:
    pad = "  " * depth
    for p in rep.get("policies") or []:
        flag = "  << VIOLATED" if p.get("violated") else ""
        print(f"{pad}{p['policy']:<20} [{p.get('state', '?'):<8}] "
              f"{p['kind']}  objective={p['objective']}{flag}")
        for w in p.get("windows") or []:
            print(f"{pad}  {_fmt_window(w)}")
        extras = []
        if "error_rate" in p:
            extras.append(f"error_rate={p['error_rate']}")
            extras.append(f"bad={p.get('bad', 0):.0f}/"
                          f"{p.get('total', 0):.0f}")
        if p.get("measured_ms") is not None:
            extras.append(f"p{int(p.get('quantile', 0.95) * 100)}="
                          f"{p['measured_ms']}ms "
                          f"(target {p.get('threshold_ms')}ms)")
        if extras:
            print(f"{pad}  {'  '.join(extras)}")
        for row in p.get("attribution") or []:
            labels = ",".join(f"{k}={v}"
                              for k, v in sorted(row["labels"].items()))
            print(f"{pad}  burning: {labels}  bad={row['bad']:.0f}")


def _run_slo_mode(base: str, fleet: bool, mode: str) -> None:
    """``--alerts`` / ``--slo``: one worker's view, or the
    coordinator's merged evaluation with per-worker blocks."""
    if not fleet:
        body = _get_json(f"{base}/{mode}")
        if mode == "alerts":
            _print_alerts_view(body)
        else:
            _print_slo_report(body)
        return
    body = _get_json(f"{base}/fleet/{mode}")
    print(f"fleet: firing={body.get('firing', 0)}")
    fleet_block = body.get("fleet") or {}
    if mode == "alerts":
        _print_alerts_view(fleet_block, 1)
    else:
        _print_slo_report(fleet_block, 1)
    for wk, view in sorted((body.get("workers") or {}).items()):
        if isinstance(view, dict) and "error" in view:
            print(f"worker {wk}: unreachable ({view['error']})",
                  file=sys.stderr)
            continue
        print(f"worker {wk}:")
        if mode == "alerts":
            _print_alerts_view(view, 1)
        else:
            _print_slo_report(view, 1)


_BLOCKS = "▁▂▃▄▅▆▇█"


def _dim(s: str) -> str:
    return f"\x1b[2m{s}\x1b[0m" if sys.stdout.isatty() else s


def _bold(s: str) -> str:
    return f"\x1b[1m{s}\x1b[0m" if sys.stdout.isatty() else s


def _labels_str(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items())) \
        or "(no labels)"


def _sparkline(values: list) -> str:
    """One series as unicode block characters, normalized to its own
    min/max (shape over scale: a latency series and a rate series are
    both readable at a glance)."""
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _BLOCKS[0] * len(values)
    return "".join(
        _BLOCKS[int((v - lo) / span * (len(_BLOCKS) - 1))]
        for v in values)


def _fmt_val(v: float) -> str:
    if v == 0:
        return "0"
    if abs(v) >= 1000:
        return f"{v:.0f}"
    if abs(v) >= 1:
        return f"{v:.2f}".rstrip("0").rstrip(".")
    return f"{v:.4g}"


def _print_query_errors(body: dict) -> None:
    for wk, err in sorted((body.get("errors") or {}).items()):
        print(f"(worker {wk} unreachable: {err})", file=sys.stderr)


def _run_query_mode(base: str, fleet: bool, expr: str) -> None:
    """``--query``: the instant value table (one row per labelset,
    worker-attributed with --fleet)."""
    url = (f"{base}/fleet/query" if fleet else f"{base}/query") \
        + f"?expr={quote(expr, safe='')}"
    body = _get_json(url)
    _print_query_errors(body)
    results = body.get("results") or []
    print(_dim(f"{expr}  at={body.get('at')}  "
               f"{len(results)} result(s)"))
    if not results:
        print("(no data — is the recorder running and the series "
              "populated?)", file=sys.stderr)
        return
    width = max(len(_labels_str(r.get("labels") or {}))
                for r in results)
    for r in results:
        print(f"  {_labels_str(r.get('labels') or {}):<{width}}  "
              f"{_bold(_fmt_val(r['value']))}")


def _run_range_mode(base: str, fleet: bool, expr: str,
                    window: float, step: float) -> None:
    """``--range``: one ANSI sparkline row per returned series —
    ``/query_range`` over the trailing ``window`` seconds at ``step``
    resolution, the worker's newest recorded data as the right
    edge."""
    url = (f"{base}/fleet/query_range" if fleet
           else f"{base}/query_range") \
        + (f"?expr={quote(expr, safe='')}&start=-{window}"
           f"&step={step}")
    body = _get_json(url)
    _print_query_errors(body)
    series = body.get("series") or []
    start, end = body.get("start"), body.get("end")
    span = f"[{start:.0f}s .. {end:.0f}s]" \
        if start is not None and end is not None else ""
    print(_dim(f"{expr}  {span} step={body.get('step', step)}s  "
               f"{len(series)} series"))
    if not series:
        print("(no data — is the recorder running and the series "
              "populated?)", file=sys.stderr)
        return
    width = max(len(_labels_str(s.get("labels") or {}))
                for s in series)
    for s in series:
        vals = [p[1] for p in s.get("points") or []
                if p[1] is not None]
        if not vals:
            continue
        print(f"  {_labels_str(s.get('labels') or {}):<{width}}  "
              f"{_sparkline(vals)}  "
              + _dim(f"min={_fmt_val(min(vals))} "
                     f"max={_fmt_val(max(vals))} "
                     f"last={_fmt_val(vals[-1])} n={len(vals)}"))


def _get_bytes(url: str, timeout: float = 30.0) -> bytes:
    with urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _fmt_ts(unix) -> str:
    if not unix:
        return "-"
    import datetime
    return datetime.datetime.fromtimestamp(float(unix)) \
        .strftime("%Y-%m-%d %H:%M:%S")


def _run_incidents_mode(base: str, fleet: bool) -> None:
    """``--incidents``: the captured-bundle inventory (fleet-wide and
    worker-attributed with --fleet), newest first."""
    if fleet:
        body = _get_json(f"{base}/fleet/incidents")
        incidents = body.get("incidents") or []
        for wk, err in sorted((body.get("errors") or {}).items()):
            print(f"(worker {wk}: {err})", file=sys.stderr)
    else:
        incidents = _get_json(f"{base}/incidents").get("incidents") or []
    for inc in incidents:
        wcol = f" {inc.get('worker', ''):<22}" if fleet else ""
        size_kb = (inc.get("bytes") or 0) / 1024.0
        state = "complete" if inc.get("complete") else "PARTIAL"
        print(f"{inc['id']:<44}{wcol} {inc.get('policy') or '?':<22} "
              f"{_fmt_ts(inc.get('at_unix')):<20} {state:<9} "
              f"files={inc.get('n_files', 0)} {size_kb:8.1f}KiB")
    if not incidents:
        print("(no incident bundles — nothing has fired, or capture "
              "is disabled)", file=sys.stderr)


def _run_fetch_mode(base: str, fleet: bool, inc_id: str,
                    out_dir: str) -> None:
    """``--fetch <id> -o dir``: download one bundle's artifacts,
    verifying each file against the manifest's SHA-256 digest. With
    --fleet the bundle is located via /fleet/incidents and fetched
    from the worker that holds it."""
    import hashlib
    import os
    if fleet:
        listing = _get_json(f"{base}/fleet/incidents")
        match = next((i for i in listing.get("incidents") or []
                      if i["id"] == inc_id), None)
        if match is None:
            raise SystemExit(f"incident {inc_id} not found on any "
                             f"worker (see --incidents)")
        base = f"http://{match['worker']}"
    info = _get_json(f"{base}/incidents/{quote(inc_id, safe='')}")
    manifest = info.get("manifest") or {}
    files = manifest.get("files") or {}
    names = sorted(set(info.get("present") or []) | set(files))
    if not names:
        raise SystemExit(f"incident {inc_id} has no artifacts")
    os.makedirs(out_dir, exist_ok=True)
    for name in names:
        body = _get_bytes(
            f"{base}/incidents/{quote(inc_id, safe='')}/{name}")
        with open(os.path.join(out_dir, name), "wb") as f:
            f.write(body)
        want = (files.get(name) or {}).get("sha256")
        got = hashlib.sha256(body).hexdigest()
        mark = ("ok" if want == got else
                ("UNVERIFIED" if want is None else "DIGEST MISMATCH"))
        print(f"  {name:<22} {len(body):>9} bytes  {mark}")
    print(f"fetched {len(names)} artifacts to {out_dir} "
          f"(complete={bool(manifest.get('complete'))})")


def _run_profile_mode(base: str, window: float,
                      baseline: float) -> None:
    """``--profile``: the always-on sampling profiler's window as a
    collapsed-stack top-table; with ``--baseline N`` the differential
    hotter-frames table instead."""
    if baseline:
        body = _get_json(f"{base}/profile/cpu?window_s={window}"
                         f"&baseline_s={baseline}")
        print(_dim(f"differential: last {window:.0f}s "
                   f"({body.get('cur_samples', 0)} samples) vs prior "
                   f"{baseline:.0f}s ({body.get('base_samples', 0)} "
                   f"samples)"))
        print(_bold(f"{'delta':>8} {'cur':>7} {'base':>7}  frame "
                    f"(hotter)"))
        for r in body.get("hotter") or []:
            print(f"{r['delta_share']:>+8.1%} {r['cur_share']:>7.1%} "
                  f"{r['base_share']:>7.1%}  {r['frame']}")
        cold = body.get("colder") or []
        if cold:
            print(_bold(f"{'delta':>8} {'cur':>7} {'base':>7}  frame "
                        f"(colder)"))
            for r in cold[:5]:
                print(f"{r['delta_share']:>+8.1%} "
                      f"{r['cur_share']:>7.1%} "
                      f"{r['base_share']:>7.1%}  {r['frame']}")
        return
    body = _get_json(f"{base}/profile/cpu?window_s={window}")
    stages = body.get("stages") or {}
    total = body.get("thread_samples") or 0
    print(_dim(f"cpu profile: last {window:.0f}s, "
               f"{body.get('samples', 0)} samples at "
               f"{body.get('hz', 0):.0f}hz"))
    if total:
        print("stages: " + "  ".join(
            f"{k}={v / total:.0%}" for k, v in stages.items()))
    print(_bold(f"{'samples':>8} {'share':>7}  stack (leaf last)"))
    for row in body.get("top_stacks") or []:
        stack = row["stack"]
        if len(stack) > 160:
            stack = "..." + stack[-157:]
        print(f"{row['count']:>8} {row['share']:>7.1%}  {stack}")
    if not body.get("top_stacks"):
        print("(no samples in the window — is the profiler enabled?)",
              file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("worker", help="worker base url, e.g. "
                                   "http://127.0.0.1:8000 (a "
                                   "coordinator url with --fleet)")
    ap.add_argument("trace_id", nargs="?",
                    help="trace to fetch (see --list)")
    ap.add_argument("--fleet", action="store_true",
                    help="URL is a ServingCoordinator: list every "
                         "worker's captures, fetch MERGED distributed "
                         "traces (per-worker Perfetto lanes)")
    ap.add_argument("--alerts", action="store_true",
                    help="print the SLO engine's compact alert view "
                         "(GET /alerts; /fleet/alerts with --fleet) "
                         "instead of traces")
    ap.add_argument("--slo", action="store_true",
                    help="print the full burn-rate report per policy "
                         "(GET /slo; /fleet/slo with --fleet) instead "
                         "of traces")
    ap.add_argument("--query", metavar="EXPR",
                    help="instant TSDB query (GET /query; /fleet/query "
                         "with --fleet): a selector, rate(sel[w]), "
                         "increase(sel[w]), or quantile(q, hist[w])")
    ap.add_argument("--range", metavar="EXPR", dest="range_expr",
                    help="range TSDB query rendered as ANSI sparklines "
                         "(GET /query_range; /fleet/query_range with "
                         "--fleet)")
    ap.add_argument("--incidents", action="store_true",
                    help="list captured incident bundles (GET "
                         "/incidents; /fleet/incidents with --fleet) — "
                         "docs/observability.md 'The postmortem plane'")
    ap.add_argument("--fetch", metavar="INCIDENT_ID",
                    help="with --incidents: download one bundle's "
                         "artifacts into the -o directory, verifying "
                         "manifest digests")
    ap.add_argument("--profile", action="store_true",
                    help="render a collapsed-stack top-table from the "
                         "always-on sampling profiler (GET "
                         "/profile/cpu?window_s=<--window>)")
    ap.add_argument("--baseline", type=float, default=0.0,
                    help="with --profile: differential mode — diff the "
                         "window against the N seconds before it and "
                         "rank frames by how much hotter they got")
    ap.add_argument("--window", type=float, default=300.0,
                    help="with --range: trailing seconds to render "
                         "(default 300); with --profile: the profile "
                         "window")
    ap.add_argument("--step", type=float, default=10.0,
                    help="with --range: evaluation step seconds "
                         "(default 10)")
    ap.add_argument("--list", action="store_true",
                    help="list retained traces and exit")
    ap.add_argument("--slow", action="store_true",
                    help="with --list: only threshold-retained traces "
                         "(drop error/shed/deadline captures; worker "
                         "mode only)")
    ap.add_argument("--slowest", action="store_true",
                    help="pick the longest retained trace instead of "
                         "naming one")
    ap.add_argument("-o", "--out", metavar="PATH",
                    help="write Perfetto/chrome://tracing trace_event "
                         "JSON here instead of printing the span tree")
    args = ap.parse_args()
    base = args.worker.rstrip("/")
    trace_base = f"{base}/fleet/trace" if args.fleet else f"{base}/trace"

    if args.alerts or args.slo:
        _run_slo_mode(base, args.fleet,
                      "alerts" if args.alerts else "slo")
        return

    if args.incidents or args.fetch:
        if args.fetch:
            _run_fetch_mode(base, args.fleet, args.fetch,
                            args.out or args.fetch)
        else:
            _run_incidents_mode(base, args.fleet)
        return
    if args.profile:
        # --window's 300s default is the --range window; profiles
        # default to the last 30s (the ring holds ~180s)
        window = args.window if args.window != 300.0 else 30.0
        _run_profile_mode(base, window, args.baseline)
        return

    if args.query:
        _run_query_mode(base, args.fleet, args.query)
        return
    if args.range_expr:
        _run_range_mode(base, args.fleet, args.range_expr,
                        args.window, args.step)
        return

    if args.list or args.slowest:
        if args.fleet:
            fleet = _get_json(f"{base}/fleet/traces")
            traces = fleet["traces"]
            for wk, err in sorted(fleet.get("errors", {}).items()):
                print(f"(worker {wk} unreachable: {err})",
                      file=sys.stderr)
        else:
            traces = _get_json(f"{base}/traces"
                               + ("?slow=1" if args.slow else ""))
        if args.list:
            _print_listing(traces, args.fleet)
            return
        if not traces:
            raise SystemExit("no retained traces to pick --slowest from")
        # both listings arrive slowest-first, but stay explicit: the
        # choice must not depend on a server-side sort contract
        args.trace_id = max(traces,
                            key=lambda t: t["duration_ms"])["trace_id"]

    if not args.trace_id:
        raise SystemExit("need a trace id, --list, or --slowest")

    try:
        if args.out:
            pf = _get_json(
                f"{trace_base}/{args.trace_id}?format=perfetto")
            with open(args.out, "w") as f:
                json.dump(pf, f)
            print(f"wrote {len(pf['traceEvents'])} events to {args.out} "
                  f"(open in chrome://tracing or ui.perfetto.dev)")
        else:
            tr = _get_json(f"{trace_base}/{args.trace_id}")
            workers = tr.get("workers")
            wline = f"  workers={','.join(workers)}" if workers else ""
            print(f"trace {tr['trace_id']}  route={tr['route']}  "
                  f"status={tr['status']}  reason={tr['reason']}  "
                  f"{tr['duration_ms']}ms{wline}")
            for wk, err in sorted(
                    (tr.get("workers_failed") or {}).items()):
                print(f"(worker {wk} unreachable: {err})",
                      file=sys.stderr)
            _print_tree(tr["tree"])
    except HTTPError as e:
        if e.code == 404:
            raise SystemExit(
                f"trace {args.trace_id} not retained (fast + ok traces "
                f"are tail-dropped; see --list)") from e
        raise


if __name__ == "__main__":
    main()
