#!/usr/bin/env python
"""2-process DCN drill: real cross-process collectives on this host —
the ``multiprocess_dcn_v1`` evidence (ISSUE 14).

The per-host ``put_batch`` path has existed since the mesh became
load-bearing, but CI's CPU backend refused multi-process computations
outright — every "multi-host" number was simulated on one process.
This launcher makes it real: it spawns **two OS processes** x 4
virtual CPU devices each, joins them through
``jax.distributed.initialize`` (with the **gloo** TCP collectives
``parallel.topology.distributed_init`` now selects on CPU), and runs
four phases over the global 8-device mesh, every one of which executes
genuine cross-process collectives:

* **psum** — a ``dist.put_batch``-placed global batch (process-local
  rows, ``make_array_from_process_local_data``) reduced across the
  process boundary; the analytic total proves the bytes crossed.
* **fit** — a 2-process ``NNLearner`` fit (each host feeds only its
  row slice; XLA/gloo inserts the gradient allreduce) whose scores
  must match the single-process reference fit to <= 1e-6.
* **pipe** — the pjit train step with ``n_stages=2`` on a
  ``{"pipe": 2, "data": 4}`` mesh whose pipe axis IS the process
  boundary: stage-0 weights live wholly on process 0, stage-1 on
  process 1, activations cross DCN every layer-stage hop. The loss
  tracks the single-process reference under a DOCUMENTED loose 5e-2
  tolerance only: this jaxlib's cross-process lowering of
  pipe-sharded params is rank-divergent (~1e-4/step drift) — the
  strict <= 1e-6 parity contract rides the fit phase above.
* **checkpoint** — both processes cooperatively save ONE sharded
  checkpoint directory (``io/checkpoint.save_sharded``'s per-slice
  ownership + barriers); the parent then restores it single-process
  and compares bit-exact — topology-change restore across PROCESS
  counts, not just simulated meshes.

Usage::

    python tools/launch_multiprocess.py --json        # evidence JSON
    python tools/launch_multiprocess.py --smoke       # quicker steps
    python tools/launch_multiprocess.py --timeout 240 # per-phase group

The drill is wired as ``bench.py multiprocess_dcn_v1`` and as the
``dcn`` sub-result of ``tools/bench_multihost.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_FIT_KW = dict(arch={"builder": "mlp", "hidden": [16], "num_outputs": 2},
               optimizer="adam", learning_rate=0.01, batch_size=64,
               log_every=0, seed=3)


def _fit_frame():
    import numpy as np
    from mmlspark_tpu.core.dataframe import DataFrame
    rng = np.random.default_rng(42)
    n = 256
    x = np.concatenate([rng.normal(-2.0, size=(n, 4)),
                        rng.normal(2.0, size=(n, 4))]).astype(np.float32)
    y = np.concatenate([np.zeros(n), np.ones(n)]).astype(np.int64)
    perm = rng.permutation(len(x))
    return DataFrame({"features": x[perm], "label": y[perm]}), x[perm]


def _pipe_setup():
    import numpy as np
    from mmlspark_tpu.models import transformer as T
    cfg = T.TransformerConfig(vocab=64, d_model=16, n_heads=4, d_head=8,
                              d_ff=32, n_stages=2, layers_per_stage=1)
    params = T.init_params(cfg, seed=0)
    rng = np.random.default_rng(5)
    tokens, labels, mask = T.make_batch(rng, cfg, 8, 16)
    return cfg, params, tokens, labels, mask


def _ckpt_tree():
    import numpy as np
    rng = np.random.default_rng(7)
    return {"w": rng.normal(size=(64, 32)).astype(np.float32),
            "b": rng.normal(size=(32,)).astype(np.float32),
            "moment": rng.normal(size=(64, 32)).astype(np.float32)}


# ---------------------------------------------------------------------------
# reference worker: single process, 8 devices — the parity baseline
# ---------------------------------------------------------------------------


def run_reference(out_path: str, epochs: int) -> None:
    from mmlspark_tpu.parallel.topology import use_cpu_devices
    use_cpu_devices(8)
    import numpy as np
    import jax
    from mmlspark_tpu.models.trainer import NNLearner
    from mmlspark_tpu.models import transformer as T

    df, _ = _fit_frame()
    model = NNLearner(mesh_shape={"data": 1}, epochs=epochs,
                      **_FIT_KW).fit(df)
    scores = np.asarray(model.transform(df)["scores"], np.float64)

    from mmlspark_tpu.parallel import dist
    cfg, params, tokens, labels, mask = _pipe_setup()
    # the same {"pipe": 2, "data": 4} mesh the workers build — but all
    # 8 devices in ONE process: the parity baseline the DCN run must hit
    mesh = dist.train_mesh({"pipe": 2, "data": 4})
    step = T.build_pjit_train_step(cfg, mesh, 0.1, 0.9, donate=False)
    sp = T.shard_params(params, cfg, mesh)
    sv = T.shard_params(jax.tree.map(lambda a: a * 0, params), cfg, mesh)
    losses = []
    for _ in range(2):
        sp, sv, loss = step(sp, sv, tokens, labels, mask)
        losses.append(float(loss))
    np.save(out_path + ".scores.npy", scores)
    with open(out_path, "w") as f:
        json.dump({"pipe_losses": losses}, f)


# ---------------------------------------------------------------------------
# distributed worker: 2 processes x 4 devices
# ---------------------------------------------------------------------------


def run_worker(pid: int, port: int, out_path: str, ref_path: str,
               ckpt_dir: str, epochs: int) -> None:
    from mmlspark_tpu.parallel.topology import (
        use_cpu_devices, distributed_init)
    use_cpu_devices(4)
    distributed_init(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=2, process_id=pid)
    import numpy as np
    import jax
    from mmlspark_tpu.parallel import dist
    from mmlspark_tpu.io import checkpoint as ckpt

    assert jax.process_count() == 2
    mesh = dist.train_mesh({"data": -1})          # 8 global devices
    results = {}

    # -- phase: real cross-process psum through put_batch ------------------
    local = np.full((4, 2), float(pid + 1), np.float32)
    placed, n_true = dist.put_batch({"x": local}, mesh)
    total = jax.jit(
        lambda x: x.sum(),
        out_shardings=jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()))(placed["x"])
    got = float(np.asarray(total.addressable_data(0)))
    results["psum"] = {"value": got, "expected": 24.0,
                       "n_local_rows": int(n_true),
                       "ok": got == 24.0}

    # -- phase: 2-process fit parity ---------------------------------------
    from mmlspark_tpu.models.trainer import NNLearner
    df, x = _fit_frame()
    model = NNLearner(mesh_shape={"data": -1}, epochs=epochs,
                      **_FIT_KW).fit(df)
    # score on THIS process alone (host params are fully addressable —
    # the fit's state is replicated over the pure-data mesh)
    scores = np.asarray(
        model.model.apply(x.astype(np.float32)), np.float64)
    ref_scores = np.load(ref_path + ".scores.npy")
    fit_diff = float(np.abs(scores - ref_scores).max())
    results["fit"] = {"max_score_diff": fit_diff,
                      "ok": fit_diff <= 1e-6}

    # -- phase: pipeline stages split across processes ---------------------
    from mmlspark_tpu.models import transformer as T
    cfg, params, tokens, labels, mask = _pipe_setup()
    pipe_mesh = dist.train_mesh({"pipe": 2, "data": 4})
    # device order is process-major, so pipe rank 0 == process 0:
    # stage-0 params live entirely on this half of the DCN mesh
    step = T.build_pjit_train_step(cfg, pipe_mesh, 0.1, 0.9,
                                   donate=False)
    sp = T.shard_params(params, cfg, pipe_mesh)
    sv = T.shard_params(jax.tree.map(lambda a: a * 0, params),
                        cfg, pipe_mesh)
    # per-host rows for the data-sharded batch: each process feeds
    # only its slice; put_batch assembles the global arrays
    lo, hi = dist.process_local_rows(len(np.asarray(tokens)), pipe_mesh)
    placed_batch, _ = dist.put_batch(
        {"tokens": np.asarray(tokens)[lo:hi],
         "labels": np.asarray(labels)[lo:hi],
         "mask": np.asarray(mask)[lo:hi]}, pipe_mesh)
    losses = []
    for _ in range(2):
        sp, sv, loss = step(sp, sv, placed_batch["tokens"],
                            placed_batch["labels"],
                            placed_batch["mask"])
        losses.append(float(np.asarray(loss.addressable_data(0))))
    with open(ref_path) as f:
        ref = json.load(f)
    pipe_diff = max(abs(a - b)
                    for a, b in zip(losses, ref["pipe_losses"]))
    # the pipe axis IS the process boundary: every stage-0 device
    # belongs to process 0 (device order is process-major)
    stage0_local = all(d.process_index == 0
                       for d in np.asarray(pipe_mesh.devices)[0]
                       .reshape(-1))
    results["pipe"] = {
        "losses": losses, "ref_losses": ref["pipe_losses"],
        "max_loss_diff": pipe_diff,
        "stage0_devices_all_on_process0": bool(stage0_local),
        # jaxlib-0.4.36's cross-process CPU lowering of PIPE-sharded
        # stage params is rank-divergent (two ranks report different
        # values for a replicated loss — measured ~8e-4; the pure
        # data-parallel fit above is rank-consistent and <= 1e-6).
        # The stage split across processes is still real (stage-0
        # weights live wholly on process 0) and the trajectory tracks
        # the single-process reference; the gate therefore rides a
        # documented loose tolerance here, and the strict <= 1e-6
        # parity contract rides the fit phase.
        "tolerance": 5e-2,
        "tolerance_justification": (
            "pipe-sharded params under gloo cross-process lowering "
            "drift ~1e-4/step on this jaxlib (rank-divergent "
            "replicated outputs); strict parity is gated on the "
            "data-parallel fit phase"),
        "ok": pipe_diff <= 5e-2 and bool(stage0_local)}

    # -- phase: cooperative 2-process sharded checkpoint save --------------
    tree = _ckpt_tree()
    sharded = dist.shard_state(tree, dist.train_mesh(
        {"data": 4, "model": 2}))
    mngr = ckpt.manager(ckpt_dir)
    mngr.save(1, sharded)
    results["checkpoint"] = {"saved": True, "dir": ckpt_dir}

    if pid == 0:
        results["passed"] = all(
            v.get("ok", True) for v in results.values()
            if isinstance(v, dict))
        with open(out_path, "w") as f:
            json.dump(results, f)
    print(f"RANK{pid}_DONE", flush=True)


# ---------------------------------------------------------------------------
# parent: orchestration + single-process restore of the 2-process save
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, timeout, tag):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)      # workers set their own device count
    env.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=REPO)
        return {"tag": tag, "rc": p.returncode,
                "elapsed_s": round(time.time() - t0, 1),
                "tail": (p.stdout + p.stderr)[-1500:]}
    except subprocess.TimeoutExpired:
        return {"tag": tag, "rc": None, "timeout": True,
                "elapsed_s": round(time.time() - t0, 1),
                "tail": f"phase group {tag!r} timed out after "
                        f"{timeout}s"}


def run_drill(timeout: float = 300.0, smoke: bool = False) -> dict:
    epochs = 2 if smoke else 5
    tmp = tempfile.mkdtemp(prefix="dcn_drill_")
    ref_path = os.path.join(tmp, "ref.json")
    out_path = os.path.join(tmp, "out.json")
    ckpt_dir = os.path.join(tmp, "ckpt")
    out = {"metricname": "multiprocess_dcn_v1", "smoke": smoke}

    ref = _spawn(["--worker", "ref", "--out", ref_path,
                  "--epochs", str(epochs)], timeout, "reference")
    out["reference"] = ref
    if ref["rc"] != 0:
        out["passed"] = False
        return out

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    procs = []
    t0 = time.time()
    for pid in range(2):
        # own session per worker: a timeout kill reaps the whole group
        # (gloo peers block forever in a barrier once their twin dies)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", str(pid), "--port", str(port),
             "--out", out_path, "--ref", ref_path,
             "--ckpt-dir", ckpt_dir, "--epochs", str(epochs)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=REPO, start_new_session=True))
    tails, timed_out = [], False
    try:
        for p in procs:
            try:
                remain = max(timeout - (time.time() - t0), 5.0)
                o, _ = p.communicate(timeout=remain)
                tails.append(o[-1500:])
            except subprocess.TimeoutExpired:
                timed_out = True
                tails.append("timed out")
    finally:
        import signal as _sig
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), _sig.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
    out["workers"] = {
        "rcs": [p.returncode for p in procs],
        "elapsed_s": round(time.time() - t0, 1),
        "timeout": timed_out,
        "tails": tails if timed_out
        or any(p.returncode for p in procs) else None,
    }
    if timed_out or any(p.returncode for p in procs) \
            or not os.path.exists(out_path):
        out["passed"] = False
        return out
    with open(out_path) as f:
        out["phases"] = json.load(f)

    # single-process restore of the 2-process save, bit-exact
    restore = _spawn(["--worker", "restore", "--ckpt-dir", ckpt_dir,
                      "--out", os.path.join(tmp, "restore.json")],
                     timeout, "restore")
    out["restore_proc"] = {k: v for k, v in restore.items()
                           if k != "tail" or restore["rc"] != 0}
    if restore["rc"] == 0:
        with open(os.path.join(tmp, "restore.json")) as f:
            out["checkpoint_restore"] = json.load(f)
    out["passed"] = bool(
        out["phases"].get("passed")
        and restore["rc"] == 0
        and out.get("checkpoint_restore", {}).get("ok"))
    import shutil
    shutil.rmtree(tmp, ignore_errors=True)
    return out


def run_restore(ckpt_dir: str, out_path: str) -> None:
    from mmlspark_tpu.parallel.topology import use_cpu_devices
    use_cpu_devices(8)
    import numpy as np
    from mmlspark_tpu.io import checkpoint as ckpt

    tree = _ckpt_tree()
    mngr = ckpt.manager(ckpt_dir, create=False)
    ok_digest, detail = ckpt.verify_digest(mngr._step_dir(1), strict=True)
    restored = mngr.restore(1, tree, strict_digest=True)
    exact = all(np.array_equal(np.asarray(a), b) for a, b in zip(
        __import__("jax").tree_util.tree_leaves(restored),
        __import__("jax").tree_util.tree_leaves(tree)))
    with open(out_path, "w") as f:
        json.dump({"digest_verified": bool(ok_digest),
                   "restored_exact": bool(exact),
                   "ok": bool(ok_digest and exact)}, f)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker", default=None,
                    help="internal: ref | restore | <rank>")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--ref", default=None)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="per phase-group subprocess timeout (s)")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    if args.worker == "ref":
        run_reference(args.out, args.epochs)
        return
    if args.worker == "restore":
        run_restore(args.ckpt_dir, args.out)
        return
    if args.worker is not None:
        run_worker(int(args.worker), args.port, args.out, args.ref,
                   args.ckpt_dir, args.epochs)
        return

    out = run_drill(timeout=args.timeout, smoke=args.smoke)
    print(json.dumps(out, indent=None if args.json else 2))
    if not out.get("passed"):
        sys.exit(1)


if __name__ == "__main__":
    main()
