"""A/B harness for the serving data plane: serial vs pipelined.

Runs the SAME load (N keep-alive clients hammering one worker with
varying-size JSON payload bursts) against a ``ServingServer`` in each
mode and reports req/s, p50/p99 latency, and the server's own
``/stats`` evidence (recompile counter, per-stage timings, bucket set):

    python tools/bench_serving_pipeline.py            # full run
    python tools/bench_serving_pipeline.py --smoke    # CPU-friendly, ~5s

Modes:

* ``serial``    — ``pipeline=False, bucket_batches=False``: the
  pre-pipeline plane (collect -> transform -> encode on one thread,
  exact batch shapes, a jit retrace per distinct size).
* ``pipelined`` — the default plane: staged collector / executor /
  encoder-pool threads + power-of-two shape buckets.

Each worker is warmed with ``ServingServer.warmup`` (one synthetic batch
per bucket shape) before the timed window, so the pipelined mode's
steady state is measured, not its warm-up — and the harness asserts
``n_recompiles`` stays flat across the timed window, which is the
"0 recompiles after warm-up" acceptance check run as code.

``--model nn`` swaps the trivial host-side model for a small jitted
``NNModel`` MLP so the A/B includes real device dispatch (on CPU this
exercises the same jit shape-cache the TPU path hits).

``--metrics-dump PATH`` additionally writes each mode's post-run
``GET /metrics`` Prometheus scrape to ``PATH.<mode>.prom`` — the full
histogram/counter evidence behind the A/B summary (see
docs/observability.md).

``--trace-dump PATH`` runs each mode with trace-everything tail
capture (``slow_trace_ms=0``) and writes the SLOWEST captured
request's Perfetto ``trace_event`` JSON to ``PATH.<mode>.trace.json``
— open it in ``chrome://tracing``/ui.perfetto.dev to see exactly
where that mode's worst request spent its time (queue wait vs pad vs
dispatch vs encode).

``--profiler-ab`` switches the harness to the POSTMORTEM-PLANE A/B
instead: the same pipelined plane with the always-on sampling CPU
profiler off vs on (stock 50 hz), interleaved rounds with medians
compared — gates the on-arm within 3% of the off-arm (the
``bench.py profiler_overhead_v1`` budget, run as a harness mode; see
docs/observability.md "The postmortem plane"):

    python tools/bench_serving_pipeline.py --profiler-ab

``--connections N`` switches the harness to the SOCKET-EDGE A/B
instead: the same pipelined data plane behind each of the two
frontends (``eventloop`` vs ``threaded`` — docs/serving.md "The
socket edge"), driven by N concurrent keep-alive connections running
strictly serial (pipelining-free) request/response cycles
(``mmlspark_tpu.testing.load``). Reports req/s, p50/p99, the
connection-reuse rate, and connection-level errors per frontend:

    python tools/bench_serving_pipeline.py --connections 1000
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

# runnable as `python tools/bench_serving_pipeline.py` from anywhere,
# same as chaos_serving.py
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _identity_model():
    from mmlspark_tpu.core.stage import Transformer

    class Identity(Transformer):
        def transform(self, df):
            return df.with_column("y", np.asarray(df["x"], dtype=np.float64))

    return Identity()


def _nn_model(wire_dtype: str = "float32"):
    from mmlspark_tpu.models.function import NNFunction
    from mmlspark_tpu.models.nn import NNModel

    fn = NNFunction.init({"builder": "mlp", "hidden": [32],
                          "num_outputs": 4}, input_shape=(8,), seed=0)
    kw = {}
    if wire_dtype != "float32":
        # the quantized wire (docs/serving.md "Quantization"):
        # one config drives the server-side cast AND the on-device
        # dequant fused into the model's first layer
        from mmlspark_tpu.serving import QuantizationConfig
        kw["quantization"] = QuantizationConfig(wire_dtype=wire_dtype,
                                                scale=1.0 / 7.0)
    return NNModel(model=fn, input_col="x", output_col="y", batch_size=64,
                   cache_inputs=False, data_parallel=False, **kw)


def _payload(model_kind: str, i: int,
             wire_dtype: str = "float32") -> bytes:
    if model_kind == "nn":
        if wire_dtype != "float32":
            return json.dumps({"x": [(i + j) % 7 for j in range(8)]}
                              ).encode()
        return json.dumps({"x": [float((i + j) % 7) for j in range(8)]}
                          ).encode()
    return json.dumps({"x": float(i)}).encode()


def _client(srv, body, counts, lat, ci, deadline, burst):
    conn = http.client.HTTPConnection(srv.host, srv.port, timeout=10)
    hdrs = {"Content-Type": "application/json"}
    while time.perf_counter() < deadline:
        # varying-size bursts: each client pauses a beat between bursts
        # so live batch sizes keep changing — the recompile trap the
        # buckets exist to defuse
        for _ in range(burst):
            t0 = time.perf_counter()
            try:
                conn.request("POST", srv.api_path, body, hdrs)
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            except OSError:
                ok = False
                conn.close()
                conn = http.client.HTTPConnection(srv.host, srv.port,
                                                  timeout=10)
            if ok:
                counts[ci] += 1
                lat[ci].append(time.perf_counter() - t0)
        time.sleep(0.001 * (1 + ci % 3))
    conn.close()


def _stats(srv) -> dict:
    conn = http.client.HTTPConnection(srv.host, srv.port, timeout=10)
    conn.request("GET", "/stats")
    out = json.loads(conn.getresponse().read())
    conn.close()
    return out


def _metrics_text(srv) -> str:
    conn = http.client.HTTPConnection(srv.host, srv.port, timeout=10)
    conn.request("GET", "/metrics")
    out = conn.getresponse().read().decode()
    conn.close()
    return out


def run_mode(mode: str, model_kind: str, n_clients: int,
             duration_s: float, max_batch_size: int,
             burst: int, metrics_dump: str = "",
             trace_dump: str = "", wire_dtype: str = "float32") -> dict:
    from mmlspark_tpu.serving import ServingServer

    model = (_nn_model(wire_dtype) if model_kind == "nn"
             else _identity_model())
    pipelined = mode == "pipelined"
    counts = [0] * n_clients
    lat = [[] for _ in range(n_clients)]
    # --trace-dump: a PRIVATE trace-everything tracer per mode (the
    # slowest request of THIS mode, not of whichever mode ran last)
    tracer = None
    if trace_dump:
        from mmlspark_tpu.core.tracing import Tracer
        tracer = Tracer(store_capacity=512)
    with ServingServer(model, max_latency_ms=2,
                       max_batch_size=max_batch_size,
                       pipeline=pipelined,
                       bucket_batches=pipelined,
                       **({"tracer": tracer, "slow_trace_ms": 0.0}
                          if tracer else {})) as srv:
        srv.warmup(json.loads(_payload(model_kind, 0, wire_dtype)))
        recompiles_warm = _stats(srv)["n_recompiles"]
        deadline = time.perf_counter() + duration_s
        threads = [threading.Thread(
            target=_client,
            args=(srv, _payload(model_kind, i, wire_dtype), counts, lat,
                  i, deadline, burst))
            for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = _stats(srv)
        dump_path = None
        if metrics_dump:
            # the post-run Prometheus scrape, written next to the A/B
            # numbers: the full histogram/counter evidence behind the
            # summary line (promtool-checkable, diffable across runs)
            dump_path = f"{metrics_dump}.{mode}.prom"
            with open(dump_path, "w") as f:
                f.write(_metrics_text(srv))
        trace_path = slowest_ms = None
        if tracer is not None:
            # the slowest captured request of this mode, as Perfetto
            # trace_event JSON — the timeline behind the p99 number
            from mmlspark_tpu.core.tracing import dump_perfetto
            retained = tracer.traces()
            if retained:
                worst = max(retained, key=lambda t: t["duration_ms"])
                slowest_ms = worst["duration_ms"]
                trace_path = dump_perfetto(
                    tracer.get_trace(worst["trace_id"]),
                    f"{trace_dump}.{mode}.trace.json")
    all_lat = sorted(x for per in lat for x in per)
    p = (lambda q: round(1000 * all_lat[int(q * (len(all_lat) - 1))], 3)) \
        if all_lat else (lambda q: None)
    return {
        "mode": mode, "model": model_kind,
        "rps": round(sum(counts) / duration_s, 1),
        "p50_ms": p(0.50), "p99_ms": p(0.99),
        "n_clients": n_clients, "duration_s": duration_s,
        "recompiles_after_warmup": stats["n_recompiles"] - recompiles_warm,
        "dispatch_sizes": stats["dispatch_sizes"],
        "stage_timings": {k: v["mean_ms"] for k, v in
                          stats["stage_timings"].items()},
        **({"metrics_dump": dump_path} if dump_path else {}),
        **({"trace_dump": trace_path,
            "slowest_trace_ms": slowest_ms} if trace_path else {}),
    }


def run_connections(frontend: str, model_kind: str, n_connections: int,
                    cycles: int, max_batch_size: int,
                    wire_dtype: str = "float32",
                    tls: bool = False) -> dict:
    """One many-connection keep-alive window against a fresh worker on
    the given socket edge (same pipelined data plane either way).
    ``tls=True`` terminates TLS at the event-loop edge (a throwaway
    self-signed cert) and drives the window over encrypted sockets."""
    import tempfile

    from mmlspark_tpu.serving import ServingServer
    from mmlspark_tpu.testing.load import drive_keepalive

    model = (_nn_model(wire_dtype) if model_kind == "nn"
             else _identity_model())
    srv_kw = {}
    client_kw = {}
    tmpdir = None
    if tls:
        from mmlspark_tpu.testing.tls import (
            client_context, generate_self_signed_cert, tls_supported)
        ok, why = tls_supported()
        if not ok:
            raise SystemExit(f"--tls unavailable: {why}")
        tmpdir = tempfile.TemporaryDirectory()
        cert, key = generate_self_signed_cert(tmpdir.name)
        srv_kw = {"tls_cert": cert, "tls_key": key}
        client_kw = {"ssl_context": client_context(cert)}
    try:
        with ServingServer(model, max_latency_ms=2,
                           max_batch_size=max_batch_size,
                           max_queue=max(4 * n_connections, 1024),
                           frontend=frontend, **srv_kw) as srv:
            srv.warmup(json.loads(_payload(model_kind, 0, wire_dtype)))
            recompiles_warm = srv.n_recompiles
            out = drive_keepalive(
                srv.host, srv.port, srv.api_path,
                _payload(model_kind, 0, wire_dtype),
                n_connections=n_connections, requests_per_conn=cycles,
                **client_kw)
            out["frontend"] = frontend
            out["tls"] = tls
            out["wire_dtype"] = wire_dtype
            out["recompiles_after_warmup"] = \
                srv.n_recompiles - recompiles_warm
            out["frontend_stats"] = srv._frontend.stats() \
                if srv._frontend is not None else {"kind": "threaded"}
    finally:
        if tmpdir is not None:
            tmpdir.cleanup()
    return out


def run_profiler_ab(model_kind: str, n_connections: int, cycles: int,
                    max_batch_size: int, rounds: int = 3) -> dict:
    """Always-on sampling profiler A/B on the pipelined plane: the
    SAME keep-alive load with ``cpu_profiler`` off vs on (the stock
    50 hz sampler), interleaved off/on rounds so host drift lands on
    both arms, medians compared. The on-arm must hold within the 3%
    budget ``bench.py profiler_overhead_v1`` gates in CI."""
    from mmlspark_tpu.serving import ServingServer
    from mmlspark_tpu.testing.load import drive_keepalive

    def arm(profiler_cfg):
        model = (_nn_model() if model_kind == "nn" else _identity_model())
        with ServingServer(model, max_latency_ms=2,
                           max_batch_size=max_batch_size,
                           max_queue=max(4 * n_connections, 1024),
                           cpu_profiler=profiler_cfg) as srv:
            srv.warmup(json.loads(_payload(model_kind, 0)))
            out = drive_keepalive(
                srv.host, srv.port, srv.api_path,
                _payload(model_kind, 0),
                n_connections=n_connections, requests_per_conn=cycles)
            status = (srv.cpu_profiler.status()
                      if srv.cpu_profiler is not None else None)
        return out["rps"], status

    arm(False)  # warm the stack off the record
    offs, ons, prof_status = [], [], None
    for _ in range(rounds):
        offs.append(arm(False)[0])
        rps_on, prof_status = arm(None)  # None = stock always-on 50 hz
        ons.append(rps_on)

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    rps_off, rps_on = med(offs), med(ons)
    delta = (rps_off - rps_on) / max(rps_off, 1e-9)
    return {"metric": "serving_profiler_ab", "model": model_kind,
            "connections": n_connections, "rounds": rounds,
            "rps_off": round(rps_off, 1), "rps_on": round(rps_on, 1),
            "rps_delta_pct": round(100 * delta, 2), "budget_pct": 3.0,
            "profiler": prof_status, "passed": delta < 0.03}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-friendly ~5s run (CI tier-1 smoke)")
    ap.add_argument("--model", choices=("identity", "nn"),
                    default="identity")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--max-batch-size", type=int, default=128)
    ap.add_argument("--burst", type=int, default=16,
                    help="requests per client burst (varies batch sizes)")
    ap.add_argument("--metrics-dump", default="", metavar="PATH",
                    help="write each mode's post-run GET /metrics scrape "
                         "to PATH.<mode>.prom next to the A/B numbers")
    ap.add_argument("--trace-dump", default="", metavar="PATH",
                    help="capture every request (slow_trace_ms=0) and "
                         "write the slowest one's Perfetto trace_event "
                         "JSON to PATH.<mode>.trace.json")
    ap.add_argument("--profiler-ab", action="store_true",
                    help="postmortem-plane A/B instead: pipelined "
                         "plane with the sampling CPU profiler off vs "
                         "on (stock 50 hz), interleaved rounds, gates "
                         "the on-arm within 3% of the off-arm")
    ap.add_argument("--connections", type=int, default=0, metavar="N",
                    help="socket-edge A/B instead: drive N concurrent "
                         "keep-alive connections against each frontend "
                         "(eventloop vs threaded) on the pipelined "
                         "plane and report req/s, p50/p99, and "
                         "connection-reuse rate per frontend")
    ap.add_argument("--cycles", type=int, default=25,
                    help="serial request/response cycles per "
                         "connection in --connections mode (reuse "
                         "rate = 1 - 1/cycles when keep-alive holds)")
    ap.add_argument("--wire-dtype", choices=("float32", "uint8"),
                    default="float32",
                    help="request wire dtype for --model nn: uint8 "
                         "rides the quantized serving plane (integer "
                         "payloads, server-side wire cast, on-device "
                         "dequant) — docs/serving.md 'The quantized "
                         "wire'")
    ap.add_argument("--tls", action="store_true",
                    help="with --connections: terminate TLS at the "
                         "event-loop edge (throwaway self-signed "
                         "cert) and A/B it against the plaintext "
                         "event loop — gates ZERO connection/HTTP "
                         "errors on the encrypted arm")
    args = ap.parse_args()
    if args.smoke:
        args.clients, args.seconds = min(args.clients, 4), 1.0
        args.max_batch_size = min(args.max_batch_size, 32)
    if args.profiler_ab:
        r = run_profiler_ab(args.model, args.connections or 16,
                            args.cycles, args.max_batch_size,
                            rounds=(1 if args.smoke else 3))
        print(json.dumps(r), flush=True)
        if not r["passed"]:
            raise SystemExit(
                f"FAIL: always-on profiler cost {r['rps_delta_pct']}% "
                "rps on the pipelined plane (budget 3%)")
        return
    if args.connections > 0:
        if args.tls:
            # TLS A/B: encrypted vs plaintext, both on the event loop
            # (the threaded plane stays the plaintext-only baseline)
            results = {}
            for arm, tls in (("tls", True), ("plaintext", False)):
                r = run_connections("eventloop", args.model,
                                    args.connections, args.cycles,
                                    args.max_batch_size,
                                    args.wire_dtype, tls=tls)
                results[arm] = r
                print(json.dumps(r), flush=True)
            enc = results["tls"]
            if enc["conn_errors"] or enc["http_errors"]:
                raise SystemExit(
                    f"FAIL: TLS edge dropped requests at "
                    f"{args.connections} connections "
                    f"({enc['conn_errors']} connection errors, "
                    f"{enc['http_errors']} HTTP errors)")
            print(json.dumps({
                "metric": "serving_tls_ab",
                "connections": args.connections,
                "tls_cost": round(
                    results["plaintext"]["rps"]
                    / max(enc["rps"], 1e-9), 3),
                "tls_reuse_rate": enc["reuse_rate"],
                "tls_handshakes":
                    enc["frontend_stats"]["tls_handshakes_total"]}),
                flush=True)
            return
        results = {}
        for fe in ("eventloop", "threaded"):
            r = run_connections(fe, args.model, args.connections,
                                args.cycles, args.max_batch_size,
                                args.wire_dtype)
            results[fe] = r
            print(json.dumps(r), flush=True)
        ev, th = results["eventloop"], results["threaded"]
        if ev["conn_errors"] or ev["http_errors"]:
            raise SystemExit(
                f"FAIL: event-loop frontend dropped requests at "
                f"{args.connections} connections "
                f"({ev['conn_errors']} connection errors, "
                f"{ev['http_errors']} HTTP errors)")
        print(json.dumps({
            "metric": "serving_frontend_ab",
            "connections": args.connections,
            "speedup": round(ev["rps"] / max(th["rps"], 1e-9), 3),
            "eventloop_reuse_rate": ev["reuse_rate"],
            "threaded_reuse_rate": th["reuse_rate"]}), flush=True)
        return
    results = {}
    for mode in ("serial", "pipelined"):
        r = run_mode(mode, args.model, args.clients, args.seconds,
                     args.max_batch_size, args.burst, args.metrics_dump,
                     args.trace_dump, args.wire_dtype)
        results[mode] = r
        print(json.dumps(r), flush=True)
    if results["pipelined"]["recompiles_after_warmup"] != 0:
        raise SystemExit(
            "FAIL: pipelined plane retraced after warm-up "
            f"({results['pipelined']['recompiles_after_warmup']} new "
            "dispatch shapes) — the bucket set is not closed")
    speedup = results["pipelined"]["rps"] / max(results["serial"]["rps"], 1)
    print(json.dumps({"metric": "serving_pipeline_ab",
                      "speedup": round(speedup, 3)}), flush=True)


if __name__ == "__main__":
    main()
