#!/usr/bin/env python
"""A/B driver for continuous-batching decode: slot-level vs whole-batch.

Runs the same seeded mixed-arrival workload through one
``TransformerDecoder`` (slot-indexed KV pool, donated cache, fixed
decode shape — ``mmlspark_tpu/serving/decode.py``) under both batching
disciplines and reports tokens/s, completion latency, and the
zero-alloc/zero-retrace evidence:

    python tools/bench_decode.py            # full run
    python tools/bench_decode.py --smoke    # CPU-friendly, ~5s

``--smoke`` (CI / tier-1-adjacent: run it under ``JAX_PLATFORMS=cpu``)
shrinks the model and workload, asserts the gates — zero post-warmup
recompiles, in-place cache donation (stable buffer pointer), zero
steady-state live-array growth, continuous >= static — and exits
non-zero on violation. It also runs the ISSUE 11 acceptance pair
(already CI-sized): ``decode_paged_v1`` (>= 2x concurrent sessions at
fixed cache HBM, dense-parity, zero recompiles, donated page pool)
and ``decode_speculative_v1`` (>= 1.3x tokens/s at measured
acceptance >= 0.6 with exact greedy parity), plus the ISSUE 15 gate
``decode_prefix_cache_v1`` (>= 1.5x prefill tokens/s at a
shared-prefix workload, exact parity, clean refcount ledger).

``--prefix-share P`` shapes the workload so fraction ``P`` of
requests draw their prompt head from a small pool of shared prefixes
(``--prefix-len`` tokens) — the same ``make_workload`` generator the
``decode_prefix_cache_v1`` gate drives — and additionally runs the
scheduler-level prefix-cache on/off A/B (prefill tokens/s, hit rate,
token parity, refcount ledger).

``--http`` additionally drives the full serving stack (HTTP ->
admission -> DecodeScheduler) with concurrent clients and reports the
server-side /decode/stats evidence, proving the wired plane matches
the engine-level numbers' contracts (compile count flat, slots all
freed).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_decoder(smoke: bool):
    from mmlspark_tpu.models import transformer as T
    from mmlspark_tpu.serving.decode import TransformerDecoder

    if smoke:
        cfg = T.TransformerConfig(vocab=128, d_model=32, n_heads=2,
                                  d_head=16, d_ff=64, n_stages=1,
                                  layers_per_stage=2)
        n_slots, max_len = 4, 64
    else:
        cfg = T.TransformerConfig(vocab=4096, d_model=256, n_heads=8,
                                  d_head=32, d_ff=1024, n_stages=1,
                                  layers_per_stage=6)
        n_slots, max_len = 16, 512
    params = T.init_params(cfg, seed=0)
    return TransformerDecoder(params, cfg, n_slots=n_slots,
                              max_len=max_len)


def run_engine_ab(decoder, smoke: bool,
                  prefix_share: float = 0.0,
                  prefix_len: int = 16) -> dict:
    from mmlspark_tpu.testing.decode_load import (
        make_workload, run_continuous, run_static,
    )
    share = dict(prefix_share=prefix_share, prefix_len=prefix_len)
    if smoke:
        jobs = make_workload(decoder.cfg.vocab, n_requests=16, seed=0,
                             mean_gap_ms=3.0, prompt_lens=(3, 5, 8),
                             max_new=(4, 8, 20), **share)
    else:
        jobs = make_workload(decoder.cfg.vocab, n_requests=96, seed=0,
                             mean_gap_ms=4.0,
                             prompt_lens=(8, 16, 32, 64),
                             max_new=(8, 32, 96), **share)
    warm = decoder.warmup()
    static = run_static(decoder, jobs)
    cont = run_continuous(decoder, jobs)
    return {"warm_compiles": warm, "static": static,
            "continuous": cont,
            "ratio": round(cont["tokens_per_s"]
                           / max(static["tokens_per_s"], 1e-9), 3)}


def run_http(decoder, n_clients: int = 8) -> dict:
    """The wired plane: concurrent clients against a live server's
    decode path."""
    import threading

    import numpy as np
    import requests

    from mmlspark_tpu.core.stage import Transformer
    from mmlspark_tpu.serving import DecodeScheduler, ServingServer

    class Identity(Transformer):
        def transform(self, df):
            return df

    sched = DecodeScheduler(decoder)
    srv = ServingServer(Identity(), port=0, decoder=sched,
                        verify_checkpoints=False)
    srv.start()
    try:
        warm = decoder.warmup()
        url = f"http://{srv.host}:{srv.port}/generate"
        rng = np.random.default_rng(0)
        errors: list = []

        def client(i: int):
            try:
                prompt = [int(t) for t in
                          rng.integers(0, decoder.cfg.vocab, size=4)]
                r = requests.post(url, json={
                    "prompt": prompt,
                    "max_new_tokens": 6 + (i % 5)}, timeout=60)
                if r.status_code != 200:
                    errors.append(f"{r.status_code}: {r.text[:80]}")
            except Exception as e:  # noqa: BLE001
                errors.append(str(e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = sched.stats()
        return {"n_clients": n_clients, "errors": errors,
                "compiles_flat": decoder.n_compiles() == warm,
                "slots_free": stats["slots_free"],
                "n_slots": stats["n_slots"],
                "decode_stats": {k: stats[k] for k in
                                 ("n_requests", "n_steps", "n_tokens",
                                  "releases")}}
    finally:
        srv.stop()


def run_prefix_ab(smoke: bool, prefix_share: float,
                  prefix_len: int) -> dict:
    """The prefix-cache A/B at the scheduler level (the engine-level
    ``run_continuous`` never touches the radix index — page sharing is
    the SCHEDULER'S machinery): the same ``--prefix-share`` workload
    through a cache-on and a cache-off scheduler, prefill tokens/s,
    hit rate, parity, and the refcount ledger."""
    from mmlspark_tpu.models import transformer as T
    from mmlspark_tpu.serving.decode import (
        DecodeScheduler, TransformerDecoder,
    )
    from mmlspark_tpu.testing.decode_load import (
        make_workload, run_scheduler_sessions,
    )

    if smoke:
        cfg = T.TransformerConfig(vocab=128, d_model=32, n_heads=2,
                                  d_head=16, d_ff=64, n_stages=1,
                                  layers_per_stage=2)
        n_slots, max_len, page, n_req = 4, 64, 8, 16
    else:
        cfg = T.TransformerConfig(vocab=4096, d_model=256, n_heads=8,
                                  d_head=32, d_ff=1024, n_stages=1,
                                  layers_per_stage=6)
        n_slots, max_len, page, n_req = 8, 512, 16, 48
    params = T.init_params(cfg, seed=0)
    jobs = make_workload(cfg.vocab, n_requests=n_req, seed=0,
                         mean_gap_ms=0.0, prompt_lens=(3, 5, 6),
                         max_new=(4, 6, 8),
                         prefix_share=prefix_share,
                         prefix_len=prefix_len)
    out = {}
    for name, prefix_on in (("off", False), ("on", True)):
        dec = TransformerDecoder(
            params, cfg, n_slots=n_slots, max_len=max_len,
            page_size=page,
            n_pages=1 + n_slots * (max_len // page)
            + 2 * (max_len // page),
            prefix_cache=prefix_on)
        sched = DecodeScheduler(dec, max_waiting=n_req + 1).start()
        try:
            dec.warmup()
            out[name] = run_scheduler_sessions(sched, jobs,
                                               rid_prefix=name)
        finally:
            sched.stop()
    out["prefill_speedup"] = round(
        out["on"]["prefill_tokens_per_s"]
        / max(out["off"]["prefill_tokens_per_s"], 1e-9), 3)
    out["token_parity"] = (out["off"]["sequences"]
                           == out["on"]["sequences"])
    for arm in ("off", "on"):
        out[arm] = {k: v for k, v in out[arm].items()
                    if k != "sequences"}
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="small model + workload, assert the gates")
    ap.add_argument("--http", action="store_true",
                    help="also drive the full HTTP serving stack")
    ap.add_argument("--prefix-share", type=float, default=0.0,
                    metavar="P",
                    help="fraction of requests drawing their prompt "
                         "head from a small pool of shared prefixes "
                         "(the prefix-cache workload knob; > 0 also "
                         "runs the scheduler-level cache A/B)")
    ap.add_argument("--prefix-len", type=int, default=40,
                    help="shared/unique prompt-head length for "
                         "--prefix-share workloads")
    args = ap.parse_args()

    decoder = build_decoder(args.smoke)
    out = {"smoke": args.smoke,
           "n_slots": decoder.n_slots, "max_len": decoder.max_len,
           "engine": run_engine_ab(decoder, args.smoke,
                                   prefix_share=args.prefix_share,
                                   prefix_len=args.prefix_len)}
    if args.prefix_share > 0:
        out["prefix"] = run_prefix_ab(args.smoke, args.prefix_share,
                                      args.prefix_len)
    if args.http:
        out["http"] = run_http(build_decoder(args.smoke))

    cont = out["engine"]["continuous"]
    gates = {
        "zero_post_warmup_recompiles":
            cont["post_warmup_recompiles"] == 0,
        "cache_donated_in_place": cont["cache_buffer_stable"],
        "zero_live_array_growth": cont["live_array_growth"] == 0,
        "continuous_beats_static": out["engine"]["ratio"] > 1.0,
    }
    if args.http:
        gates["http_compiles_flat"] = out["http"]["compiles_flat"]
        gates["http_no_errors"] = not out["http"]["errors"]
        gates["http_slots_all_freed"] = (out["http"]["slots_free"]
                                         == out["http"]["n_slots"])
    if args.prefix_share > 0:
        gates["prefix_token_parity"] = out["prefix"]["token_parity"]
        gates["prefix_ledger_clean"] = \
            out["prefix"]["on"]["pages_all_freed"]
        gates["prefix_hits"] = \
            out["prefix"]["on"]["prefix_cache"]["hits"] > 0
    if args.smoke:
        # the ISSUE 11 acceptance pair + the ISSUE 15 prefix-cache
        # gate, CI-sized already: paged sessions-at-fixed-HBM,
        # speculative tokens/s, and prefix-cache prefill tokens/s
        # A/Bs, each with recompile/donation/parity gates baked in
        import bench as _bench
        paged = _bench.bench_decode_paged()
        spec = _bench.bench_decode_speculative()
        prefix = _bench.bench_decode_prefix_cache()
        out["paged"] = {k: paged[k] for k in
                        ("value", "baseline", "vs_baseline",
                         "tokens_per_s", "token_parity", "passed")}
        out["speculative"] = {k: spec[k] for k in
                              ("value", "baseline", "vs_baseline",
                               "acceptance_rate", "token_parity",
                               "passed")}
        out["prefix_cache"] = {k: prefix[k] for k in
                               ("value", "baseline", "vs_baseline",
                                "hit_rate", "token_parity",
                                "ledger_clean", "passed")}
        gates["paged_2x_sessions_at_fixed_hbm"] = paged["passed"]
        gates["speculative_speedup"] = spec["passed"]
        gates["prefix_cache_prefill_speedup"] = prefix["passed"]
        # the ISSUE 17 raw-speed pair: Pallas flash prefill (no [S,S]
        # score matrix, token parity incl. offset prefill) and int8
        # on-device compute staged through rollout verify/rollback
        flash = _bench.bench_prefill_flash()
        qc = _bench.bench_quantized_compute()
        out["prefill_flash"] = {k: flash[k] for k in
                                ("value", "baseline", "vs_baseline",
                                 "attn_impl", "token_parity",
                                 "no_ss_in_jaxpr",
                                 "post_warmup_recompiles", "passed")}
        out["quantized_compute"] = {k: qc[k] for k in
                                    ("value", "baseline",
                                     "vs_baseline", "live_parity_ok",
                                     "post_flip_recompiles",
                                     "rollback_drill", "passed")}
        gates["prefill_flash"] = flash["passed"]
        gates["quantized_compute"] = qc["passed"]
    out["gates"] = gates
    out["passed"] = all(gates.values())
    print(json.dumps(out, indent=2))
    return 0 if out["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
