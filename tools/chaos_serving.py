"""Chaos-drive a serving fleet: kill/restart a worker mid-traffic under
a seeded FaultPlan and report recovery stats.

The multi-process companion to ``tests/test_resilience.py``: real OS
worker processes (the same ``ServingServer`` the k8s pods run), a real
coordinator, and a ``ServingClient`` pushing idempotent traffic while
the plan SIGKILLs a worker and later restarts it — the pod-crash drill,
reproducible from a seed. Exit code 0 iff every request was answered
correctly and no request was computed more than once per accepted
execution (journals verified via each worker's ``GET /status``).

    python tools/chaos_serving.py                 # defaults: 120 reqs
    python tools/chaos_serving.py --requests 300 --kill-at 40 \
        --restart-after 30 --seed 7

After the kill/restart drill, a second phase drives a concurrent
KEEP-ALIVE burst (N client threads sharing one ``ServingClient``, whose
pooled session holds a persistent connection per worker) and SIGKILLs a
worker mid-burst: the drill asserts the failover path retries every
affected request onto the survivors with ZERO dropped requests — the
in-flight requests already accepted by the surviving worker all
complete — and that the survivor's frontend counters prove the burst
actually rode kept-alive connections. ``--burst-threads 0`` skips the
phase.

A third phase drills the ZERO-DOWNTIME ROLLOUT machinery
(docs/serving.md "Zero-downtime rollout"): a fresh fleet of workers
serving a persisted v1 checkpoint, idempotent client traffic, then a
coordinator-orchestrated ``POST /rollout`` to a v2 checkpoint with
canary enabled — and one worker SIGKILLed in the middle of it. The
drill asserts the rollout still ends ``completed`` (survivors finish
the flip), ``GET /fleet`` reports ONE coherent version set across the
responding workers, and no logical client request was dropped or
answered wrongly at any point. ``--rollout-workers 0`` skips the phase.

A fourth phase drills the decode plane's CROSS-REQUEST PREFIX CACHE
(docs/serving.md "Prefix cache"): a live decode worker serves a
shared-prefix burst twice — pass 1 cold (prompt pages publish into
the radix index), pass 2 the same prompts under fresh rids (cached
pages attach, only suffixes prefill) — and the drill asserts hit
rate > 0, ZERO wrong tokens (pass 2 token-for-token equals pass 1),
and a clean refcount ledger on drain. ``--prefix-requests 0`` skips
it; ``--prefix-only`` runs JUST this phase (the fast smoke mode).

A fifth phase is the NOISY-NEIGHBOR drill (docs/serving.md "Tenancy &
overload control"): a two-worker tenancy-enabled fleet, a background
tenant flooding keep-alive connections at both workers while an
interactive tenant sends steady idempotent traffic through a
SIGKILL + journal-replay restart of one worker. Pass iff the
interactive tenant's error rate is ZERO (every logical request
answered correctly through the kill), its flooded p99 stays within
2x its quiet baseline (floored against dev-box jitter), the flood
tenant is actually shed (429s on the wire and ``n_shed_overload`` in
its ledger rows), every tenant ledger drains clean (inflight 0, no
release underflow), the restarted worker replayed a non-empty
journal, and the coordinator's ``GET /fleet`` merges both tenants'
rows. ``--tenancy-requests 0`` skips the phase.

A sixth phase drills the fleet SLO plane (docs/observability.md
"SLO engine"): a two-worker fleet behind a coordinator running fast
burn-rate windows, steady traffic proving ZERO false-positive alerts,
then one worker SIGKILLed — the drill asserts ``GET /fleet/alerts``
FIRES the ``fleet_availability`` policy with the victim (and only the
victim) in the per-worker attribution, and that after a replacement
worker heartbeats in the alert RESOLVES and the healed fleet stays
quiet. ``--slo-alerts-requests 0`` skips the phase.

A seventh phase drills the RETROSPECTIVE PLANE's baseline-relative
regression detection (docs/observability.md "The retrospective
plane"): an in-process worker with an embedded TSDB running a fast
recording rule over dispatch-latency p95 and an anomaly watch on the
rule's series. Steady traffic establishes the EWMA+MAD baseline
(ZERO false positives allowed); then the model is made 80 ms slower
mid-traffic and the drill asserts the ``dispatch_p95_regression``
anomaly FIRES on ``GET /alerts`` with per-bucket attribution; then
the slowdown is reverted, the short quantile window drains, and the
alert must RESOLVE and stay quiet. ``--regression-requests 0`` skips
the phase.

Runs on CPU; phases 1-2 need no model artifact (workers serve an
inline doubler); phase 3 persists real ``ScaleColumn`` checkpoints.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER_SCRIPT = """
import sys, time
from mmlspark_tpu.serving.server import ServingServer, ServingCoordinator
from mmlspark_tpu.core.stage import Transformer
import numpy as np

class Doubler(Transformer):
    def transform(self, df):
        return df.with_column("y", np.asarray(df["x"], dtype=np.float64) * 2)

srv = ServingServer(Doubler(), max_latency_ms=1,
                    journal_path=sys.argv[2],
                    slow_trace_ms=0.0).start()
ServingCoordinator.register_worker(sys.argv[1], srv.host, srv.port)
print(srv.port, flush=True)
while True:
    time.sleep(1)
"""


DECODE_WORKER_SCRIPT = """
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from mmlspark_tpu.models import transformer as T
from mmlspark_tpu.serving import DecodeScheduler, ServingServer, \\
    TransformerDecoder
from mmlspark_tpu.core.stage import Transformer

class Identity(Transformer):
    def transform(self, df):
        return df

cfg = T.TransformerConfig(vocab=128, d_model=32, n_heads=2, d_head=16,
                          d_ff=64, n_stages=1, layers_per_stage=2)
dec = TransformerDecoder(T.init_params(cfg, seed=0), cfg, n_slots=4,
                         max_len=64, page_size=8)
sched = DecodeScheduler(dec)
srv = ServingServer(Identity(), port=0, decoder=sched,
                    max_latency_ms=1, journal_path=sys.argv[2],
                    verify_checkpoints=False).start()
dec.warmup()
print(srv.port, flush=True)
while True:
    time.sleep(1)
"""


ROLLOUT_WORKER_SCRIPT = """
import sys, time
from mmlspark_tpu.serving.server import ServingServer, ServingCoordinator
from mmlspark_tpu.core.stage import PipelineStage

model = PipelineStage.load(sys.argv[2])
srv = ServingServer(model, max_latency_ms=1, max_batch_size=8,
                    journal_path=sys.argv[3], model_version="v1",
                    slow_trace_ms=None)
srv.warmup({"x": 0.0})
srv.start()
ServingCoordinator.register_worker(sys.argv[1], srv.host, srv.port)
print(srv.port, flush=True)
while True:
    time.sleep(1)
"""


TENANCY_WORKER_SCRIPT = """
import sys, time
from mmlspark_tpu.serving.server import ServingServer, ServingCoordinator
from mmlspark_tpu.core.stage import Transformer
import numpy as np

class SlowDoubler(Transformer):
    # a fixed 2 ms per-batch cost: the worker, not the shared-host
    # client fleet, is the bottleneck, so the flood builds real queue
    # depth for the shed/fair-share machinery to act on
    def transform(self, df):
        time.sleep(0.002)
        return df.with_column("y", np.asarray(df["x"], dtype=np.float64) * 2)

srv = ServingServer(SlowDoubler(), max_latency_ms=2, max_batch_size=8,
                    max_queue=32, tenancy=sys.argv[2],
                    journal_path=sys.argv[3],
                    slow_trace_ms=None).start()
ServingCoordinator.register_worker(sys.argv[1], srv.host, srv.port)
print(srv.port, flush=True)
while True:
    time.sleep(1)
"""


SLO_WORKER_SCRIPT = """
import sys, time
from mmlspark_tpu.serving.server import ServingServer, ServingCoordinator
from mmlspark_tpu.core.stage import Transformer
import numpy as np

class Doubler(Transformer):
    def transform(self, df):
        return df.with_column("y", np.asarray(df["x"], dtype=np.float64) * 2)

srv = ServingServer(Doubler(), max_latency_ms=1,
                    journal_path=sys.argv[2],
                    slow_trace_ms=None).start()
print(srv.port, flush=True)
while True:
    # heartbeat: re-register every 0.5 s so the coordinator's
    # stale_after prunes the SIGKILLed worker but never a live one —
    # the same contract `python -m mmlspark_tpu.serving worker` keeps
    ServingCoordinator.register_worker(sys.argv[1], srv.host, srv.port)
    time.sleep(0.5)
"""


def spawn_worker(coord_url: str, journal: str,
                 script: str = WORKER_SCRIPT, *extra) -> "subprocess.Popen":
    env = dict(os.environ, PYTHONPATH=REPO)
    p = subprocess.Popen(
        [sys.executable, "-c", script, coord_url, *extra, journal],
        stdout=subprocess.PIPE, env=env, text=True)
    port = p.stdout.readline().strip()
    if not port:
        raise RuntimeError(f"worker died on spawn (rc={p.poll()})")
    p.port = int(port)  # type: ignore[attr-defined]
    return p


def worker_status(port: int) -> dict:
    import requests
    try:
        return requests.get(f"http://127.0.0.1:{port}/status",
                            timeout=5).json()
    except Exception:  # noqa: BLE001 — dead worker has no status
        return {}


def keepalive_burst_drill(coord_url: str, workers: list,
                          kill_index: int, n_threads: int,
                          per_thread: int, seed: int) -> dict:
    """Phase 2: concurrent keep-alive burst, one worker killed mid-way.

    ``n_threads`` client threads share ONE ServingClient (pooled
    session = persistent connection per worker); after each thread has
    finished ~1/3 of its requests, worker ``kill_index`` is SIGKILLed.
    Every logical request must still return the right answer — the
    attempts in flight on the dead worker fail over, and the requests
    the SURVIVORS had already accepted all complete (zero drops)."""
    import threading

    import requests

    from mmlspark_tpu.serving.server import ServingClient

    client = ServingClient(coord_url, timeout=10)
    survivor_port = workers[1 - kill_index].port
    reuses_before = requests.get(
        f"http://127.0.0.1:{survivor_port}/stats", timeout=5
    ).json()["frontend"].get("keepalive_reuses_total", 0)
    results: dict = {}
    errors: list = []
    kill_gate = threading.Barrier(n_threads + 1)

    def burst(ti: int) -> None:
        for j in range(per_thread):
            if j == per_thread // 3:
                kill_gate.wait()      # every thread is mid-burst here
            rid = f"burst-{seed}-{ti}-{j}"
            x = float(ti * per_thread + j)
            try:
                results[rid] = client.predict({"x": x},
                                              request_id=rid)
            except Exception as e:  # noqa: BLE001 — a dropped request
                errors.append({"rid": rid, "error": str(e)})

    threads = [threading.Thread(target=burst, args=(ti,))
               for ti in range(n_threads)]
    for t in threads:
        t.start()
    kill_gate.wait()                  # all threads in flight
    os.kill(workers[kill_index].pid, signal.SIGKILL)
    workers[kill_index].wait()
    for t in threads:
        t.join()

    def expected(rid: str) -> dict:
        _, _, ti, j = rid.rsplit("-", 3)
        return {"y": 2.0 * (int(ti) * per_thread + int(j))}

    n_wrong = sum(1 for rid, out in results.items()
                  if out != expected(rid))
    survivor = requests.get(
        f"http://127.0.0.1:{survivor_port}/stats", timeout=5).json()
    reuses_during = survivor["frontend"].get(
        "keepalive_reuses_total", 0) - reuses_before
    total = n_threads * per_thread
    return {
        "what": "keep-alive burst with a mid-burst worker kill",
        "n_threads": n_threads, "per_thread": per_thread,
        "total_requests": total,
        "n_ok": len(results) - n_wrong, "n_wrong": n_wrong,
        "n_dropped": len(errors), "dropped": errors[:5],
        "n_failovers": client.n_failovers,
        "survivor_keepalive_reuses": reuses_during,
        "ok": (len(results) == total and n_wrong == 0
               and not errors and client.n_failovers > 0
               and reuses_during > 0),
    }


def rollout_drill(tmp: str, seed: int, n_workers: int = 3) -> dict:
    """Phase 3: kill a worker in the middle of a canary rollout.

    A fresh fleet serves a persisted v1 ``ScaleColumn`` checkpoint;
    idempotent client traffic runs throughout; the coordinator
    orchestrates ``POST /rollout`` to a v2 checkpoint (canary on); one
    NON-canary worker is SIGKILLed once the rollout is under way. Pass
    iff the rollout ends ``completed``, ``GET /fleet`` shows one
    coherent version set (``["v2"]``) across responding workers, and
    every logical client request was answered correctly (v1 or v2
    output — the flip is mid-traffic — but never an error or a drop).
    """
    import threading

    import requests

    from mmlspark_tpu.serving.server import (
        ServingClient, ServingCoordinator)
    from mmlspark_tpu.stages import ScaleColumn

    v1_dir = os.path.join(tmp, "model_v1")
    v2_dir = os.path.join(tmp, "model_v2")
    ScaleColumn(input_col="x", output_col="y", scale=2.0).save(v1_dir)
    ScaleColumn(input_col="x", output_col="y", scale=3.0).save(v2_dir)

    coord = ServingCoordinator().start()
    coord_url = f"http://{coord.host}:{coord.port}"
    workers = [
        spawn_worker(coord_url,
                     os.path.join(tmp, f"r{i}.jsonl"),
                     ROLLOUT_WORKER_SCRIPT, v1_dir)
        for i in range(n_workers)]
    stats = {"n_ok": 0, "n_wrong": 0, "dropped": [],
             "killed_during": None}
    stop = threading.Event()
    client = ServingClient(coord_url, timeout=10)

    def traffic() -> None:
        i = 0
        while not stop.is_set():
            i += 1
            rid = f"rollout-{seed}-{i}"
            x = float(i)
            try:
                out = client.predict({"x": x}, request_id=rid)
            except Exception as e:  # noqa: BLE001 — a dropped request
                stats["dropped"].append({"rid": rid, "error": str(e)})
                continue
            # the flip is mid-traffic: v1 (2x) and v2 (3x) replies are
            # both correct; anything else is a wrong answer
            if out.get("y") in (2.0 * x, 3.0 * x):
                stats["n_ok"] += 1
            else:
                stats["n_wrong"] += 1

    t = threading.Thread(target=traffic)
    t.start()
    try:
        # canary_min_requests is sized so the canary phase lasts long
        # enough (roughly a second under this traffic) for the kill to
        # land genuinely mid-rollout, not after it
        r = requests.post(coord_url + "/rollout", json={
            "path": v2_dir, "version": "v2", "canary": True,
            "warmup_payload": {"x": 0.0},
            "canary_window_s": 8.0, "canary_min_requests": 150,
            "poll_interval_s": 0.05}, timeout=10)
        assert r.status_code == 202, r.text
        # kill a NON-canary worker (the orchestrator canaries the
        # first registered) once the rollout is past staging
        deadline = time.perf_counter() + 30
        state = "pending"
        while time.perf_counter() < deadline:
            state = requests.get(coord_url + "/rollout",
                                 timeout=10).json()["state"]
            if state in ("canary", "flipping", "completed",
                         "rolled_back", "failed"):
                break
            time.sleep(0.05)
        stats["killed_during"] = state
        os.kill(workers[-1].pid, signal.SIGKILL)
        workers[-1].wait()
        # wait for the rollout to reach a terminal state
        deadline = time.perf_counter() + 60
        final = None
        while time.perf_counter() < deadline:
            final = requests.get(coord_url + "/rollout",
                                 timeout=10).json()
            if final["state"] in ("completed", "rolled_back", "failed"):
                break
            time.sleep(0.1)
        fleet = requests.get(coord_url + "/fleet", timeout=10).json()
    finally:
        stop.set()
        t.join()
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        coord.stop()
    ok = (final is not None and final["state"] == "completed"
          and stats["killed_during"] in ("canary", "flipping")
          and fleet["model_versions"] == ["v2"]
          and fleet["version_coherent"]
          and fleet["n_responding"] == n_workers - 1
          and stats["n_wrong"] == 0 and not stats["dropped"]
          and stats["n_ok"] > 0)
    return {
        "what": "kill one worker mid-canary-rollout; survivors must "
                "finish the flip",
        "n_workers": n_workers,
        "rollout": {"state": final["state"] if final else None,
                    "decision": final.get("decision") if final else None,
                    "workers": final.get("workers") if final else None},
        "killed_during": stats["killed_during"],
        "fleet_versions": fleet["model_versions"],
        "version_coherent": fleet["version_coherent"],
        "n_responding": fleet["n_responding"],
        "traffic": {"n_ok": stats["n_ok"], "n_wrong": stats["n_wrong"],
                    "n_dropped": len(stats["dropped"]),
                    "dropped": stats["dropped"][:5]},
        "ok": ok,
    }


def prefix_drill(tmp: str, seed: int, n_requests: int = 16) -> dict:
    """Phase 4 (smoke-fast, CPU-only): a shared-prefix decode burst
    through a LIVE decode worker — the cross-request prefix cache
    drill (docs/serving.md "Prefix cache").

    Pass 1 sends ``n_requests`` shared-prefix prompts cold (their
    prompt pages publish into the radix index on finish); pass 2
    replays the SAME prompts under fresh request ids, so they attach
    the cached pages and prefill only their suffixes. Asserts: the
    worker's ``/decode/stats`` shows a hit rate > 0, pass 2's tokens
    match pass 1's token-for-token (ZERO wrong tokens — cached pages
    served exactly what cold prefill computed), and on drain the
    refcount ledger is clean (free + cached == claimable,
    ``ledger_clean``)."""
    import requests

    from mmlspark_tpu.testing.decode_load import make_workload

    w = spawn_worker("unused", os.path.join(tmp, "decode.jsonl"),
                     script=DECODE_WORKER_SCRIPT)
    url = f"http://127.0.0.1:{w.port}"
    jobs = make_workload(128, n_requests=n_requests, seed=seed,
                         mean_gap_ms=0.0, prompt_lens=(3, 5),
                         max_new=(4, 6), prefix_share=0.75,
                         prefix_len=24, prefix_pool=2)
    try:
        passes = []
        for pi in range(2):
            toks, errors = [], 0
            for i, job in enumerate(jobs):
                r = requests.post(
                    url + "/generate",
                    json={"prompt": [int(t) for t in job.prompt],
                          "max_new_tokens": int(job.max_new)},
                    headers={"X-Request-Id":
                             f"prefix-{seed}-{pi}-{i}"},
                    timeout=30)
                if r.status_code != 200:
                    errors += 1
                    toks.append(None)
                else:
                    toks.append(r.json()["tokens"])
            passes.append({"tokens": toks, "errors": errors})
        stats = requests.get(url + "/decode/stats",
                             timeout=10).json()
        pc = stats["prefix_cache"]
        pages = stats["pages"]
        wrong = sum(1 for a, b in zip(passes[0]["tokens"],
                                      passes[1]["tokens"]) if a != b)
        ledger_clean = (pc["ledger_clean"]
                        and pages["free"] + pages["cached"]
                        == pages["n_pages"])
        ok = (passes[0]["errors"] == passes[1]["errors"] == 0
              and wrong == 0
              and (pc["hit_rate"] or 0) > 0
              and pc["hit_tokens"] > 0
              and ledger_clean)
        return {"n_requests": n_requests, "n_passes": 2,
                "errors": [p["errors"] for p in passes],
                "wrong_tokens": wrong,
                "hit_rate": pc["hit_rate"],
                "hit_tokens": pc["hit_tokens"],
                "cached_pages": pc["cached_pages"],
                "evicted_pages": pc["evicted_pages"],
                "ledger_clean": ledger_clean,
                "ok": ok}
    finally:
        if w.poll() is None:
            w.kill()
            w.wait()


def tenancy_drill(tmp: str, seed: int, n_requests: int = 300) -> dict:
    """Phase 5: noisy neighbor vs. interactive tenant, through a kill.

    A two-worker tenancy-enabled fleet (API-key admission, priority
    shed at ``high_water=0.5``, deficit-weighted fair-share). Tenant
    ``bob`` (background) floods keep-alive connections at BOTH
    workers; tenant ``alice`` (interactive) sends steady idempotent
    traffic through a ``ServingClient`` the whole time — including a
    SIGKILL of worker 0 mid-flood and its journal-replay restart.

    Pass iff alice's error rate is ZERO (every logical request
    answered, correctly), her flooded steady-state p99 holds within
    2x her quiet baseline (floored at 50 ms against shared-host
    jitter; the handful of requests that rode the kill's failover
    schedule are reported as ``kill_spikes_ms`` and gated by the
    zero-drop check, not the p99), bob is actually shed (429s on his
    wire AND
    ``n_shed_overload`` in his ledger rows), every tenant ledger
    drains clean (inflight 0, zero release underflow, zero per-IP
    underflow), the restarted worker replayed a non-empty journal,
    and ``GET /fleet`` merges both tenants' rows."""
    import threading

    import requests

    from mmlspark_tpu.serving.server import (
        ServingClient, ServingCoordinator)
    from mmlspark_tpu.testing.load import drive_keepalive

    tenancy_path = os.path.join(tmp, "tenants.json")
    with open(tenancy_path, "w", encoding="utf-8") as f:
        json.dump({
            "unknown_key_policy": "reject",
            "high_water": 0.5,
            "fair_share": True,
            "tenants": [
                {"id": "alice", "priority": "interactive",
                 "api_keys": ["drill-alice"], "weight": 8.0},
                {"id": "bob", "priority": "background",
                 "api_keys": ["drill-bob"], "weight": 1.0},
            ],
        }, f)

    coord = ServingCoordinator().start()
    coord_url = f"http://{coord.host}:{coord.port}"
    workers = [
        spawn_worker(coord_url, os.path.join(tmp, f"t{i}.jsonl"),
                     TENANCY_WORKER_SCRIPT, tenancy_path)
        for i in range(2)]
    client = ServingClient(coord_url, timeout=10,
                           api_key="drill-alice")
    stats = {"killed_at": None, "restarted_at": None,
             "n_ok": 0, "n_wrong": 0, "failed_rids": []}
    flood: dict = {}

    def flood_worker(name: str, port: int, dur: float) -> None:
        flood[name] = drive_keepalive(
            "127.0.0.1", port, "/predict", b'{"x": 1.0}',
            n_connections=30, duration_s=dur,
            extra_headers=[("X-Api-Key", "drill-bob")])

    def pct99(lat: list) -> float:
        if not lat:
            return 0.0
        s = sorted(lat)
        return s[min(int(0.99 * len(s)), len(s) - 1)] * 1000.0

    try:
        # alice's quiet baseline: the fleet all to herself
        quiet_lat = []
        for i in range(max(60, n_requests // 4)):
            t0 = time.perf_counter()
            out = client.predict({"x": float(i)},
                                 request_id=f"tq-{seed}-{i}")
            quiet_lat.append(time.perf_counter() - t0)
            if out != {"y": 2.0 * i}:
                stats["n_wrong"] += 1

        # bob floods both workers while alice keeps her steady loop
        # running THROUGH worker 0's SIGKILL and restart
        flood_s = 15.0
        threads = [
            threading.Thread(target=flood_worker,
                             args=(f"w{i}", w.port, flood_s))
            for i, w in enumerate(workers)]
        for t in threads:
            t.start()
        flooded_lat = []
        kill_spikes = []
        kill_at, restart_at = n_requests // 3, 2 * n_requests // 3
        for i in range(n_requests):
            if i == kill_at:
                os.kill(workers[0].pid, signal.SIGKILL)
                workers[0].wait()
                stats["killed_at"] = i
            if i == restart_at:
                workers[0] = spawn_worker(
                    coord_url, os.path.join(tmp, "t0.jsonl"),
                    TENANCY_WORKER_SCRIPT, tenancy_path)
                client.refresh()
                stats["restarted_at"] = i
            rid = f"tf-{seed}-{i}"
            x = float(1000 + i)
            f0 = client.n_failovers
            t0 = time.perf_counter()
            try:
                out = client.predict({"x": x}, request_id=rid)
            except Exception as e:  # noqa: BLE001 — a dropped request
                stats["failed_rids"].append({"rid": rid,
                                             "error": str(e)})
                continue
            dt = time.perf_counter() - t0
            # the few requests that rode the kill's failover schedule
            # carry recovery latency (phase-1 territory, gated by the
            # zero-drop check); the tenancy p99 gate is about QUEUEING
            # isolation, so it reads the steady-state requests
            if client.n_failovers == f0:
                flooded_lat.append(dt)
            else:
                kill_spikes.append(dt)
            if out == {"y": 2.0 * x}:
                stats["n_ok"] += 1
            else:
                stats["n_wrong"] += 1
        for t in threads:
            t.join()
        time.sleep(0.5)   # let shed replies and closes drain

        per_worker = []
        for w in workers:
            try:
                per_worker.append(requests.get(
                    f"http://127.0.0.1:{w.port}/stats",
                    timeout=5).json())
            except Exception:  # noqa: BLE001 — dead worker
                per_worker.append({})
        fleet = requests.get(coord_url + "/fleet", timeout=10).json()
        recovered = (worker_status(workers[0].port)
                     .get("journal_recovered") or 0)
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        coord.stop()

    rows = [r for s in per_worker
            for r in ((s.get("tenancy") or {}).get("tenants") or [])]
    bob_shed = sum(r["n_shed_overload"] + r["n_shed_rate"]
                   for r in rows if r["id"] == "bob")
    bob_429 = sum(f["http_errors"] for f in flood.values())
    ledger_clean = (
        rows
        and all(r["inflight"] == 0 and r["n_release_underflow"] == 0
                for r in rows)
        and all((s.get("frontend") or {})
                .get("per_ip_underflow_total", 0) == 0
                for s in per_worker if s))
    fleet_ids = {r["id"] for r in (fleet.get("tenants") or [])}
    quiet_p99 = pct99(quiet_lat)
    flooded_p99 = pct99(flooded_lat)
    p99_bound = max(2.0 * quiet_p99, 50.0)
    ok = (stats["n_ok"] == n_requests
          and stats["n_wrong"] == 0
          and not stats["failed_rids"]
          and flooded_p99 <= p99_bound
          and bob_429 > 0 and bob_shed > 0
          and ledger_clean
          and recovered > 0
          and {"alice", "bob"} <= fleet_ids)
    return {
        "what": "background flood vs. steady interactive tenant, "
                "through a worker SIGKILL + journal-replay restart",
        "n_requests": n_requests,
        "killed_at": stats["killed_at"],
        "restarted_at": stats["restarted_at"],
        "alice": {"n_ok": stats["n_ok"], "n_wrong": stats["n_wrong"],
                  "n_dropped": len(stats["failed_rids"]),
                  "dropped": stats["failed_rids"][:5],
                  "quiet_p99_ms": round(quiet_p99, 3),
                  "flooded_p99_ms": round(flooded_p99, 3),
                  "p99_bound_ms": round(p99_bound, 3),
                  "kill_spikes_ms": [round(s * 1000.0, 3)
                                     for s in kill_spikes],
                  "n_failovers": client.n_failovers},
        "bob": {"wire_429s": bob_429, "shed_total": bob_shed,
                "rps": [f["rps"] for f in flood.values()]},
        "ledger_clean": bool(ledger_clean),
        "journal_recovered": recovered,
        "fleet_tenants": sorted(fleet_ids),
        "ok": ok,
    }


def slo_alerts_drill(tmp: str, seed: int, n_requests: int = 16) -> dict:
    """Phase 6: the SLO availability-burn drill (docs/observability.md
    "SLO engine").

    A two-worker fleet behind a coordinator whose fleet SLO plane runs
    fast burn windows. Steady-state traffic + ``GET /fleet/alerts``
    polls must stay QUIET (zero false positives); then worker 0 is
    SIGKILLed and the drill asserts the ``fleet_availability`` policy
    FIRES with the victim — and only the victim — in the per-worker
    attribution; then a replacement worker heartbeats in, the dead
    registration ages out of ``stale_after``, the burn decays, and the
    alert must RESOLVE and stay quiet.
    """
    import requests
    from mmlspark_tpu.serving.server import ServingClient, \
        ServingCoordinator

    # fast windows so the drill runs in seconds: objective 0.9 means a
    # 1-dead-of-2 fleet (50% poll failures) burns 5x budget — well
    # over the 1.0 threshold — while a healthy fleet burns 0.
    coord = ServingCoordinator(
        stale_after=6.0,
        slo={"objective": 0.9,
             "windows": ((15.0, 3.0, 1.0),),
             "for_s": 0.0,
             "resolve_after_s": 2.0}).start()
    coord_url = f"http://{coord.host}:{coord.port}"
    workers = [spawn_worker(coord_url, os.path.join(tmp, f"slo{i}.jsonl"),
                            SLO_WORKER_SCRIPT)
               for i in range(2)]
    out: dict = {"what": "SIGKILL one of two workers; fleet_availability "
                         "must fire with victim attribution, then "
                         "resolve after a replacement heartbeats in"}

    def fleet_alerts():
        return requests.get(coord_url + "/fleet/alerts",
                            timeout=10).json()

    def availability_alert(view):
        for alert in (view.get("fleet") or {}).get("alerts") or []:
            if alert.get("policy") == "fleet_availability":
                return alert
        return None

    try:
        # wait for both heartbeats to land before judging quiet
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            svcs = requests.get(coord_url + "/services",
                                timeout=10).json()
            if len(svcs) >= 2:
                break
            time.sleep(0.1)
        client = ServingClient(coord_url, timeout=10)
        victim = f"127.0.0.1:{workers[0].port}"
        survivor = f"127.0.0.1:{workers[1].port}"

        # -- steady state: traffic + alert polls, ZERO firing allowed
        false_firing = 0
        for i in range(max(n_requests, 4)):
            client.predict({"x": i}, request_id=f"slo-{seed}-{i}")
            if fleet_alerts()["firing"]:
                false_firing += 1
            time.sleep(0.15)
        out["steady_polls"] = max(n_requests, 4)
        out["steady_false_firing"] = false_firing

        # -- kill: poll until the availability policy fires
        os.kill(workers[0].pid, signal.SIGKILL)
        workers[0].wait()
        fired = attributed = False
        survivor_blamed = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            alert = availability_alert(fleet_alerts())
            if alert is not None and alert["state"] == "firing":
                fired = True
                blamed = {row["labels"].get("worker")
                          for row in alert.get("attribution") or []}
                attributed = victim in blamed
                survivor_blamed = survivor in blamed
                break
            time.sleep(0.25)
        out["fired"] = fired
        out["victim_attributed"] = attributed
        out["survivor_blamed"] = survivor_blamed

        # -- restart: replacement heartbeats in; the dead registration
        # ages out of stale_after; failures stop; the short window
        # drains; the alert must resolve within the quiet period
        workers[0] = spawn_worker(
            coord_url, os.path.join(tmp, "slo0b.jsonl"),
            SLO_WORKER_SCRIPT)
        resolved = False
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline:
            view = fleet_alerts()
            alert = availability_alert(view)
            state = alert["state"] if alert is not None else "ok"
            if view["firing"] == 0 and state in ("ok", "resolved"):
                resolved = True
                break
            time.sleep(0.5)
        out["resolved"] = resolved

        # -- post-resolve: the healed fleet must stay quiet
        post_false = 0
        for _ in range(4):
            if fleet_alerts()["firing"]:
                post_false += 1
            time.sleep(0.25)
        out["post_resolve_false_firing"] = post_false
        out["ok"] = (false_firing == 0 and fired and attributed
                     and not survivor_blamed and resolved
                     and post_false == 0)
        return out
    finally:
        for w in workers:
            try:
                w.kill()
            except Exception:  # noqa: BLE001 — already dead
                pass
        coord.stop()


def regression_drill(tmp: str, seed: int, n_requests: int = 60) -> dict:
    """Phase 7: the latency-regression anomaly drill
    (docs/observability.md "The retrospective plane").

    One in-process worker whose embedded TSDB runs a FAST recording
    rule (``chaos:dispatch_p95`` = p95 of dispatch latency over a 4 s
    window, 0.1 s scrape cadence) and an anomaly watch on that rule's
    series. Steady traffic warms the EWMA+MAD baseline and must stay
    QUIET (zero false positives); then the model is made 80 ms slower
    mid-traffic — the watch must FIRE on ``GET /alerts`` with the
    dispatch histogram's per-bucket labels as attribution; then the
    slowdown is reverted, the 4 s window drains the slow
    observations, and the alert must RESOLVE within the quiet period
    and stay quiet after."""
    import numpy as np
    import requests

    from mmlspark_tpu.core.stage import Transformer
    from mmlspark_tpu.serving import ServingServer

    class SlowableDoubler(Transformer):
        delay_s = 0.0

        def transform(self, df):
            if self.delay_s:
                time.sleep(self.delay_s)
            return df.with_column(
                "y", np.asarray(df["x"], dtype=np.float64) * 2)

    model = SlowableDoubler()
    # the rule's 4 s quantile window is what lets the drill resolve in
    # seconds: after the revert, the slow observations age out of the
    # window and the p95 series comes back to baseline. min_abs=10ms
    # floors the z-score against a near-zero steady MAD (dispatch of
    # a doubler is sub-millisecond), so only the injected regression
    # can violate.
    tsdb_cfg = {
        "interval_s": 0.1,
        "rules": [{"record": "chaos:dispatch_p95",
                   "expr":
                       "quantile(0.95, serving_dispatch_latency_ms[4s])"}],
        "watches": [{"name": "dispatch_p95_regression",
                     "expr": "chaos:dispatch_p95",
                     "direction": "high", "z_threshold": 4.0,
                     "min_samples": 20, "min_abs": 10.0,
                     "for_s": 0.3, "resolve_after_s": 1.0}],
    }
    out: dict = {"what": "inject an 80ms model slowdown mid-traffic; "
                         "the dispatch-p95 anomaly watch must fire "
                         "with bucket attribution, then resolve on "
                         "revert"}

    with ServingServer(model, max_batch_size=4, max_latency_ms=5,
                       tsdb=tsdb_cfg) as srv:
        base = srv.address.rsplit("/", 1)[0]

        def anomaly(view):
            for alert in view.get("anomalies") or []:
                if alert.get("watch") == "dispatch_p95_regression":
                    return alert
            return None

        def pump(stop_fn, max_s, gap_s=0.03):
            """Send traffic until ``stop_fn`` returns truthy or the
            deadline passes; returns (stop_fn result, n_firing_polls,
            n_requests_sent)."""
            i = 0
            firing_polls = 0
            deadline = time.monotonic() + max_s
            while time.monotonic() < deadline:
                requests.post(srv.address,
                              json={"x": float(i % 7)}, timeout=10)
                i += 1
                if i % 4 == 0:
                    view = requests.get(base + "/alerts",
                                        timeout=10).json()
                    if view["firing"]:
                        firing_polls += 1
                    got = stop_fn(view)
                    if got:
                        return got, firing_polls, i
                time.sleep(gap_s)
            return None, firing_polls, i

        # -- steady state: warm the baseline well past min_samples
        # (20 ticks at 0.1 s) and prove the watch stays quiet
        warm = max(n_requests, 40)
        steady_end = time.monotonic() + max(warm * 0.05, 5.0)
        _, false_polls, n_sent = pump(
            lambda view: time.monotonic() >= steady_end,
            max_s=max(warm * 0.05, 5.0) + 5.0)
        out["steady_requests"] = n_sent
        out["steady_false_firing"] = false_polls

        # -- inject: 80 ms regression; the watch must fire with the
        # dispatch histogram's bucket label as attribution
        model.delay_s = 0.08
        alert, _, _ = pump(
            lambda view: (a := anomaly(view)) is not None
            and a["state"] == "firing" and a, max_s=25.0)
        out["fired"] = alert is not None
        out["attributed"] = bool(
            alert and "bucket" in (alert.get("labels") or {}))
        out["fired_value_ms"] = alert and alert.get("value")
        out["baseline_ms"] = alert and alert.get("baseline")

        # -- revert: the window drains, the alert must resolve
        model.delay_s = 0.0
        resolved, _, _ = pump(
            lambda view: view["firing"] == 0
            and (a := anomaly(view)) is not None
            and a["state"] in ("ok", "resolved") and a, max_s=30.0)
        out["resolved"] = resolved is not None

        # -- post-resolve: healed traffic must stay quiet
        _, post_false, _ = pump(lambda view: False, max_s=2.0)
        out["post_resolve_false_firing"] = post_false
        out["recorder"] = {
            k: srv.recorder.status()[k]
            for k in ("n_scrapes", "ewma_ingest_ms", "n_over_budget",
                      "n_rule_errors")}
        out["ok"] = (false_polls == 0 and out["fired"]
                     and out["attributed"] and out["resolved"]
                     and post_false == 0
                     and out["recorder"]["n_rule_errors"] == 0)
        return out


def postmortem_drill(tmp: str, seed: int, n_requests: int = 40) -> dict:
    """Phase 8: the incident-capture drill (docs/observability.md
    "The postmortem plane").

    The phase-7 latency regression, re-run against a worker with the
    always-on sampling profiler and an IncidentManager wired to the
    anomaly notifier. Steady traffic must produce ZERO bundles; the
    injected 80 ms slowdown must (a) fire the anomaly, (b) land one
    COMPLETE on-disk bundle containing a non-empty profile, at least
    one retained trace, and the violated series range, and (c) show
    the injected-delay frame (this drill's ``transform``) in the
    differential profile's top hotter-frames table; the revert must
    resolve the alert; a second regression inside the cooldown must
    be suppressed (no duplicate bundle)."""
    import numpy as np
    import requests

    from mmlspark_tpu.core.stage import Transformer
    from mmlspark_tpu.serving import ServingServer

    class SlowableDoubler(Transformer):
        delay_s = 0.0

        def transform(self, df):
            if self.delay_s:
                time.sleep(self.delay_s)
            return df.with_column(
                "y", np.asarray(df["x"], dtype=np.float64) * 2)

    model = SlowableDoubler()
    # phase 7's fast detector (4 s rule window, 0.1 s cadence,
    # min_abs=10ms floor) plus the postmortem plane: tight incident
    # knobs so the drill runs in seconds (a short profile post-window,
    # a 30 s series lookback at 0.5 s resolution) and a 60 s cooldown
    # long enough that the second injection below MUST be suppressed.
    tsdb_cfg = {
        "interval_s": 0.1,
        "rules": [{"record": "chaos:dispatch_p95",
                   "expr":
                       "quantile(0.95, serving_dispatch_latency_ms[4s])"}],
        "watches": [{"name": "dispatch_p95_regression",
                     "expr": "chaos:dispatch_p95",
                     "direction": "high", "z_threshold": 4.0,
                     "min_samples": 20, "min_abs": 10.0,
                     "for_s": 0.3, "resolve_after_s": 1.0}],
    }
    inc_dir = os.path.join(tmp, "incidents")
    incidents_cfg = {"dir": inc_dir, "cooldown_s": 60.0,
                     "profile_pre_s": 8.0, "profile_post_s": 0.5,
                     "lookback_s": 30.0, "series_step_s": 0.5}
    out: dict = {"what": "phase-7 regression with incident capture: "
                         "firing must snapshot a complete bundle "
                         "(profile + traces + series + logs + stats), "
                         "steady state must write nothing, a repeat "
                         "inside the cooldown must be suppressed"}

    with ServingServer(model, max_batch_size=4, max_latency_ms=5,
                       tsdb=tsdb_cfg, incidents=incidents_cfg,
                       slow_trace_ms=40.0,
                       adaptive_slow_trace=False) as srv:
        base = srv.address.rsplit("/", 1)[0]

        def anomaly(view):
            for alert in view.get("anomalies") or []:
                if alert.get("watch") == "dispatch_p95_regression":
                    return alert
            return None

        def pump(stop_fn, max_s, gap_s=0.03):
            i = 0
            deadline = time.monotonic() + max_s
            while time.monotonic() < deadline:
                requests.post(srv.address,
                              json={"x": float(i % 7)}, timeout=10)
                i += 1
                if i % 4 == 0:
                    view = requests.get(base + "/alerts",
                                        timeout=10).json()
                    got = stop_fn(view)
                    if got:
                        return got
                time.sleep(gap_s)
            return None

        # -- steady state: warm the baseline; nothing may be captured
        warm_s = max(max(n_requests, 40) * 0.05, 5.0)
        steady_end = time.monotonic() + warm_s
        pump(lambda view: time.monotonic() >= steady_end,
             max_s=warm_s + 5.0)
        steady = requests.get(base + "/incidents", timeout=10).json()
        out["steady_bundles"] = steady["status"]["captured"]

        # -- inject: the watch fires AND the incident manager captures
        model.delay_s = 0.08
        t_inject = time.monotonic()
        alert = pump(
            lambda view: (a := anomaly(view)) is not None
            and a["state"] == "firing" and a, max_s=25.0)
        out["fired"] = alert is not None

        # differential profile WHILE the regression runs: the injected
        # delay (this drill's ``transform``, parked in time.sleep)
        # must top the hotter-frames table
        time.sleep(1.0)        # let the hot window accumulate samples
        window_s = max(time.monotonic() - t_inject, 2.0)
        diff = requests.get(
            base + f"/profile/cpu?window_s={window_s:.1f}"
                   f"&baseline_s=8", timeout=10).json()
        hot = [r["frame"] for r in (diff.get("hotter") or [])[:10]]
        out["diff_top_hotter"] = hot[:5]
        out["diff_names_delay_frame"] = any(
            ":transform:" in f for f in hot)

        # the bundle: wait for the capture thread (profile post-window
        # is 0.5 s), then verify completeness + contents over HTTP —
        # exactly what an operator's tooling would read
        srv.incidents.wait_idle(timeout=20.0)
        listing = requests.get(base + "/incidents", timeout=10).json()
        out["bundles_after_fire"] = listing["status"]["captured"]
        bundle_ok = profile_ok = traces_ok = series_ok = False
        if listing["incidents"]:
            inc = listing["incidents"][0]
            inc_id = inc["id"]
            out["incident_id"] = inc_id
            info = requests.get(base + f"/incidents/{inc_id}",
                                timeout=10).json()
            bundle_ok = info["complete"] and all(
                f in info["present"] for f in
                ("alert.json", "series.json", "traces.json",
                 "logs.json", "stats.json", "profile.collapsed",
                 "manifest.json"))
            prof = requests.get(
                base + f"/incidents/{inc_id}/profile.collapsed",
                timeout=10).text
            profile_ok = len(prof.strip()) > 0
            traces = requests.get(
                base + f"/incidents/{inc_id}/traces.json",
                timeout=10).json()
            traces_ok = len(traces.get("traces") or []) >= 1
            series = requests.get(
                base + f"/incidents/{inc_id}/series.json",
                timeout=10).json()
            own = (series.get("series") or {}).get("chaos:dispatch_p95",
                                                   {})
            vals = [p[1] for s in own.get("series") or []
                    for p in s.get("points") or []
                    if p[1] is not None]
            # the violated range: the regressed p95 (>= the watch's
            # 10 ms min_abs floor; steady state is sub-millisecond)
            series_ok = bool(vals) and max(vals) >= 10.0
            out["series_max_ms"] = max(vals) if vals else None
        out["bundle_complete"] = bundle_ok
        out["profile_nonempty"] = profile_ok
        out["traces_retained"] = traces_ok
        out["series_violated_range"] = series_ok

        # -- revert: the alert must resolve, and resolving must NOT
        # write another bundle
        model.delay_s = 0.0
        resolved = pump(
            lambda view: view["firing"] == 0
            and (a := anomaly(view)) is not None
            and a["state"] in ("ok", "resolved") and a, max_s=30.0)
        out["resolved"] = resolved is not None

        # -- duplicate suppression: a second regression inside the
        # 60 s cooldown fires again but must NOT produce a new bundle
        model.delay_s = 0.08
        refired = pump(
            lambda view: (a := anomaly(view)) is not None
            and a["state"] == "firing" and a, max_s=25.0)
        model.delay_s = 0.0
        srv.incidents.wait_idle(timeout=20.0)
        status = requests.get(base + "/incidents",
                              timeout=10).json()["status"]
        out["refired"] = refired is not None
        out["bundles_after_refire"] = status["captured"]
        out["suppressed_by_cooldown"] = status["suppressed_cooldown"]

        out["ok"] = (out["steady_bundles"] == 0
                     and out["fired"]
                     and out["bundles_after_fire"] == 1
                     and bundle_ok and profile_ok and traces_ok
                     and series_ok
                     and out["diff_names_delay_frame"]
                     and out["resolved"]
                     and out["refired"]
                     and out["bundles_after_refire"] == 1
                     and out["suppressed_by_cooldown"] >= 1)
        return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--kill-at", type=int, default=30,
                    help="SIGKILL worker 0 after this many requests")
    ap.add_argument("--restart-after", type=int, default=30,
                    help="restart it this many requests later")
    ap.add_argument("--seed", type=int, default=0,
                    help="FaultPlan seed (request-id stream)")
    ap.add_argument("--burst-threads", type=int, default=8,
                    help="phase-2 keep-alive burst client threads "
                         "(0 skips the phase)")
    ap.add_argument("--burst-requests", type=int, default=15,
                    help="requests per burst thread")
    ap.add_argument("--rollout-workers", type=int, default=3,
                    help="phase-3 kill-mid-rollout drill fleet size "
                         "(0 skips the phase; needs >= 3 so a "
                         "non-canary worker can die)")
    ap.add_argument("--prefix-requests", type=int, default=16,
                    help="phase-4 shared-prefix decode burst size "
                         "(0 skips the phase)")
    ap.add_argument("--prefix-only", action="store_true",
                    help="run ONLY the phase-4 prefix-cache drill "
                         "(the fast smoke mode)")
    ap.add_argument("--tenancy-requests", type=int, default=300,
                    help="phase-5 noisy-neighbor drill: interactive "
                         "requests through the flood (0 skips the "
                         "phase)")
    ap.add_argument("--slo-alerts-requests", type=int, default=16,
                    help="phase-6 SLO availability-burn drill: steady-"
                         "state requests before the SIGKILL (0 skips "
                         "the phase)")
    ap.add_argument("--regression-requests", type=int, default=60,
                    help="phase-7 latency-regression anomaly drill: "
                         "steady-state requests before the injected "
                         "slowdown (0 skips the phase)")
    ap.add_argument("--postmortem-requests", type=int, default=40,
                    help="phase-8 incident-capture drill: steady-state "
                         "requests before the injected regression that "
                         "must land a complete on-disk incident bundle "
                         "(0 skips the phase)")
    args = ap.parse_args()

    if args.prefix_only:
        tmp = tempfile.mkdtemp(prefix="chaos_prefix_")
        drill = prefix_drill(tmp, args.seed,
                             n_requests=args.prefix_requests or 16)
        print(json.dumps({"what": "prefix-cache drill (smoke)",
                          "prefix": drill}, indent=2))
        print("RESULT:", "PASS" if drill["ok"] else "FAIL")
        return 0 if drill["ok"] else 1

    from mmlspark_tpu.serving.server import (
        ServingClient, ServingCoordinator)
    from mmlspark_tpu.testing.faults import FaultPlan

    # the plan is bookkeeping here: it records the kill/restart schedule
    # so the run's chaos is part of its report (and a future
    # rate-driven schedule stays seeded)
    plan = FaultPlan(seed=args.seed,
                     script={"proc": ["ok"] * args.kill_at + ["kill"]})

    tmp = tempfile.mkdtemp(prefix="chaos_serving_")
    coord = ServingCoordinator().start()
    coord_url = f"http://{coord.host}:{coord.port}"
    workers = [spawn_worker(coord_url, os.path.join(tmp, f"w{i}.jsonl"))
               for i in range(2)]
    stats = {"killed_at": None, "restarted_at": None, "n_ok": 0,
             "n_wrong": 0, "failed_rids": [],
             "first_ok_after_kill": None}
    t0 = time.perf_counter()
    try:
        client = ServingClient(coord_url, timeout=10)
        restart_at = None
        for i in range(args.requests):
            fault = plan.at("proc")
            if fault.kind == "kill" and stats["killed_at"] is None:
                os.kill(workers[0].pid, signal.SIGKILL)
                workers[0].wait()
                stats["killed_at"] = i
                restart_at = i + args.restart_after
            if restart_at is not None and i == restart_at:
                # with worker 0 still dead, the coordinator's fleet
                # trace view must DEGRADE, not fail: the dead worker
                # becomes an error entry and the survivors' captures
                # (every request — the workers trace everything) are
                # still listed with worker attribution
                import requests
                ft = requests.get(coord_url + "/fleet/traces",
                                  timeout=10).json()
                live_workers = {t["worker"] for t in ft["traces"]}
                stats["fleet_dead_errors"] = len(ft["errors"])
                stats["fleet_live_captures"] = len(ft["traces"])
                stats["fleet_traces_ok"] = (
                    len(ft["errors"]) >= 1
                    and f"127.0.0.1:{workers[1].port}" in live_workers)
                workers[0] = spawn_worker(
                    coord_url, os.path.join(tmp, "w0.jsonl"))
                client.refresh()
                stats["restarted_at"] = i
            rid = f"chaos-{args.seed}-{i}"
            try:
                out = client.predict({"x": i}, request_id=rid)
            except Exception as e:  # noqa: BLE001 — report, don't crash
                stats["failed_rids"].append({"rid": rid, "error": str(e)})
                continue
            if out == {"y": 2.0 * i}:
                stats["n_ok"] += 1
                if stats["killed_at"] is not None \
                        and stats["first_ok_after_kill"] is None:
                    stats["first_ok_after_kill"] = i
            else:
                stats["n_wrong"] += 1
        burst = None
        if args.burst_threads > 0:
            # phase 2: kill worker 1 (worker 0 was already killed and
            # restarted above) in the middle of a concurrent keep-alive
            # burst, then bring a replacement up so the fleet ends the
            # drill whole
            burst = keepalive_burst_drill(
                coord_url, workers, kill_index=1,
                n_threads=args.burst_threads,
                per_thread=args.burst_requests, seed=args.seed)
            workers[1] = spawn_worker(
                coord_url, os.path.join(tmp, "w1.jsonl"))
        rollout = None
        if args.rollout_workers > 0:
            rollout = rollout_drill(tmp, args.seed,
                                    n_workers=max(args.rollout_workers,
                                                  3))
        prefix = None
        if args.prefix_requests > 0:
            prefix = prefix_drill(tmp, args.seed,
                                  n_requests=args.prefix_requests)
        tenancy = None
        if args.tenancy_requests > 0:
            tenancy = tenancy_drill(tmp, args.seed,
                                    n_requests=args.tenancy_requests)
        slo_alerts = None
        if args.slo_alerts_requests > 0:
            slo_alerts = slo_alerts_drill(
                tmp, args.seed, n_requests=args.slo_alerts_requests)
        regression = None
        if args.regression_requests > 0:
            regression = regression_drill(
                tmp, args.seed, n_requests=args.regression_requests)
        postmortem = None
        if args.postmortem_requests > 0:
            postmortem = postmortem_drill(
                tmp, args.seed, n_requests=args.postmortem_requests)
        wall = time.perf_counter() - t0

        per_worker = [worker_status(w.port) for w in workers]
        report = {
            "what": "serving chaos drill: kill/restart worker 0 under "
                    "idempotent client traffic",
            "args": {"requests": args.requests, "kill_at": args.kill_at,
                     "restart_after": args.restart_after,
                     "seed": args.seed},
            "plan": plan.summary(),
            "stats": stats,
            "client": {"n_failovers": client.n_failovers,
                       "breakers": client.breakers.states()},
            "workers": [{k: s.get(k) for k in
                         ("n_requests", "n_replayed", "n_shed",
                          "journal_recovered")} for s in per_worker],
            **({"burst": burst} if burst is not None else {}),
            **({"rollout": rollout} if rollout is not None else {}),
            **({"prefix": prefix} if prefix is not None else {}),
            **({"tenancy": tenancy} if tenancy is not None else {}),
            **({"slo_alerts": slo_alerts}
               if slo_alerts is not None else {}),
            **({"regression": regression}
               if regression is not None else {}),
            **({"postmortem": postmortem}
               if postmortem is not None else {}),
            "wall_s": round(wall, 3),
        }
        print(json.dumps(report, indent=2))
        # the restarted worker committed replies before the kill, so a
        # correct restart MUST have replayed a non-empty journal; 0
        # means the durable-journal story is broken
        recovered = stats["restarted_at"] is None or \
            (per_worker[0].get("journal_recovered") or 0) > 0
        ok = (stats["n_ok"] == args.requests
              and stats["n_wrong"] == 0
              and not stats["failed_rids"]
              and recovered
              and stats.get("fleet_traces_ok", True)
              and (burst is None or burst["ok"])
              and (rollout is None or rollout["ok"])
              and (prefix is None or prefix["ok"])
              and (tenancy is None or tenancy["ok"])
              and (slo_alerts is None or slo_alerts["ok"])
              and (regression is None or regression["ok"])
              and (postmortem is None or postmortem["ok"]))
        print("RESULT:", "PASS" if ok else "FAIL")
        return 0 if ok else 1
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        coord.stop()


if __name__ == "__main__":
    sys.exit(main())
