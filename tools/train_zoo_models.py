"""Train and publish the in-repo model zoo (offline, one-time).

The reference's ModelDownloader serves *trained* CNTK nets
(`ModelDownloader.scala:54,124`); this is the offline converter/trainer
that fills the same role here (SURVEY §7 step 4). Three models:

- ``digits_resnet8`` — ResNet-8 on sklearn's real 8x8 digits dataset,
  classes 0-7 ONLY (8/9 held out so the transfer-learning example is
  genuine: its features were never trained on the target classes).
- ``digits32_resnet14`` — ResNet-14 on the SAME real digits upscaled to
  32x32 (classes 0-7; 8/9 held out): the real-data model above 8x8 —
  its accuracy gate and transfer tests are claims about real data, not
  a surrogate.
- ``cifar10s_resnet20`` — ResNet-20 on CIFAR-scale 32x32x3 data, 10
  classes, trained ON TPU with the device-resident epoch-scan fit
  (uint8 on the wire, normalize + flip/crop augmentation on device).
  It trains on REAL CIFAR-10 whenever the standard
  ``cifar-10-batches-py`` files are present ($CIFAR10_DIR or
  ``zoo/data/cifar-10-batches-py``); this build environment has zero
  network egress and no CIFAR files on disk, so the committed weights
  come from the deterministic procedural surrogate
  (`testing/datagen.synth_cifar` — pattern families 0-9; 10-11 stay
  unseen for transfer). The manifest's ``dataset`` field records which
  corpus trained the published weights.

Run from the repo root:
    python tools/train_zoo_models.py [digits|digits32|cifar]
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ZOO = os.path.join(REPO, "zoo")
GOLDEN = os.path.join(REPO, "tests", "resources", "golden_digits_resnet8.npz")
GOLDEN_CIFAR = os.path.join(REPO, "tests", "resources",
                            "golden_cifar10s_resnet20.npz")
ARCH = {"builder": "cifar_resnet", "depth": 8, "width": 8, "num_classes": 8}
ARCH_CIFAR = {"builder": "cifar_resnet", "depth": 20, "num_classes": 10}
ARCH_D32 = {"builder": "cifar_resnet", "depth": 14, "num_classes": 8}
GOLDEN_D32 = os.path.join(REPO, "tests", "resources",
                          "golden_digits32_resnet14.npz")


def load_digits_pretrain_split():
    """Digits 0-7, deterministic train/test split (8/9 left for transfer)."""
    from sklearn.datasets import load_digits
    d = load_digits()
    images = (d.images / 16.0).astype(np.float32)[..., None]  # (n, 8, 8, 1)
    labels = d.target.astype(np.int64)
    keep = labels < 8
    images, labels = images[keep], labels[keep]
    rng = np.random.default_rng(0)
    order = rng.permutation(len(images))
    images, labels = images[order], labels[order]
    n_test = 200
    return (images[n_test:], labels[n_test:],
            images[:n_test], labels[:n_test])


def _publish_and_golden(fn, name, dataset, model_type, input_shape,
                        num_classes, acc, golden_path, probe_x,
                        golden_target, input_dtype=None):
    """Shared publish + golden-fixture scaffold for TPU-trained models:
    register the weights in the zoo, then write the fixture placeholder
    and re-exec this script on the CPU TEST backend to fill the logits
    (20 layers of f32 convs drift ~5e-2 between TPU and CPU while the
    zoo tests pin at 1e-4 — the fixture must come from the backend the
    tests run on)."""
    from mmlspark_tpu.models.zoo import ModelRepo
    kw = {"input_dtype": input_dtype} if input_dtype else {}
    meta = ModelRepo(ZOO).publish(name, fn, dataset=dataset,
                                  model_type=model_type,
                                  input_shape=input_shape,
                                  num_classes=num_classes, **kw)
    print(f"published {meta.name}: hash={meta.hash[:12]}... -> {meta.uri}")
    os.makedirs(os.path.dirname(golden_path), exist_ok=True)
    np.savez(golden_path, x=probe_x,
             logits=np.zeros((len(probe_x), num_classes), np.float32),
             test_accuracy=acc)
    import subprocess
    subprocess.run([sys.executable, os.path.abspath(__file__),
                    golden_target], check=True)
    print(f"golden fixture (CPU-backend logits) -> {golden_path}")


def _regen_golden(name, golden_path, input_scale=1.0):
    """Fill a golden fixture's logits from the published weights on the
    CPU test backend (run in a fresh process; see _publish_and_golden)."""
    from mmlspark_tpu.models.zoo import ModelDownloader
    g = np.load(golden_path)
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        fn = ModelDownloader(tmp, repo=ZOO).load(name)
    logits = np.asarray(
        fn.apply(g["x"].astype(np.float32) * input_scale),
        dtype=np.float32)
    np.savez(golden_path, x=g["x"], logits=logits,
             test_accuracy=g["test_accuracy"])


def load_digits32_split():
    """REAL sklearn digits upscaled to 32x32 (classes 0-7; 8/9 held out
    for transfer) — the largest real-data scale available in this
    zero-egress environment above the 8x8 original."""
    from mmlspark_tpu.ops.image import resize
    Xtr, ytr, Xte, yte = load_digits_pretrain_split()
    up = lambda a: np.asarray(resize(a, 32, 32), dtype=np.float32)
    return up(Xtr), ytr, up(Xte), yte


def train_digits32() -> None:
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.trainer import NNLearner
    from mmlspark_tpu.models.zoo import ModelRepo

    Xtr, ytr, Xte, yte = load_digits32_split()
    print(f"digits32 split: {len(Xtr)} train / {len(Xte)} test (REAL "
          f"sklearn digits, upscaled 8x8 -> 32x32)")

    # no flip augmentation: mirrored digits are different glyphs
    learner = NNLearner(arch=ARCH_D32, epochs=60, batch_size=256,
                        learning_rate=0.04, warmup_steps=100,
                        clip_norm=1.0, device_resident=True,
                        log_every=10, seed=0)
    model = learner.fit(DataFrame({"features": Xtr, "label": ytr}))

    scored = model.transform(DataFrame({"features": Xte, "label": yte}))
    acc = float((np.asarray(scored["scores"]).argmax(axis=1) == yte).mean())
    print(f"test accuracy (REAL digits, classes 0-7): {acc:.4f}")
    if acc < 0.95:
        raise SystemExit(f"refusing to publish a weak model (acc={acc:.3f})")

    rng = np.random.default_rng(123)
    probe = rng.uniform(0, 1, size=(8, 32, 32, 1)).astype(np.float32)
    _publish_and_golden(model.model, "digits32_resnet14",
                        dataset="sklearn-digits-32x32(0-7)",
                        model_type="cifar_resnet/14",
                        input_shape=[32, 32, 1], num_classes=8, acc=acc,
                        golden_path=GOLDEN_D32, probe_x=probe,
                        golden_target="digits32-golden")


def regen_digits32_golden() -> None:
    _regen_golden("digits32_resnet14", GOLDEN_D32)


def load_cifar_split():
    """Real CIFAR-10 if the standard batches exist, else the committed
    procedural surrogate (50k train / 10k test, classes 0-9)."""
    from mmlspark_tpu.testing.datagen import load_cifar10_batches, synth_cifar
    for d in (os.environ.get("CIFAR10_DIR", ""),
              os.path.join(ZOO, "data", "cifar-10-batches-py")):
        if d and os.path.exists(os.path.join(d, "data_batch_1")):
            print(f"using REAL CIFAR-10 from {d}")
            return load_cifar10_batches(d) + ("cifar-10",)
    print("real CIFAR-10 not on disk (zero-egress build env); "
          "using the deterministic procedural surrogate")
    Xtr, ytr = synth_cifar(50_000, seed=0)
    Xte, yte = synth_cifar(10_000, seed=1_000_003)
    return Xtr, ytr, Xte, yte, "synth-cifar10-v1(procedural)"


def train_cifar() -> None:
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.trainer import NNLearner
    from mmlspark_tpu.models.zoo import ModelRepo

    Xtr, ytr, Xte, yte, dataset = load_cifar_split()
    print(f"cifar split: {len(Xtr)} train / {len(Xte)} test ({dataset})")

    learner = NNLearner(arch=ARCH_CIFAR, epochs=24, batch_size=512,
                        learning_rate=0.05, warmup_steps=200,
                        clip_norm=1.0, device_resident=True,
                        augment="flip_crop", log_every=1, seed=0)
    model = learner.fit(DataFrame({"features": Xtr, "label": ytr}))

    scored = model.transform(DataFrame({"features": Xte, "label": yte}))
    acc = float((np.asarray(scored["scores"]).argmax(axis=1) == yte).mean())
    print(f"test accuracy (10 classes): {acc:.4f}")
    floor = 0.85 if dataset == "cifar-10" else 0.90
    if acc < floor:
        raise SystemExit(f"refusing to publish a weak model (acc={acc:.3f})")

    rng = np.random.default_rng(123)
    probe = rng.integers(0, 256, size=(8, 32, 32, 3), dtype=np.uint8)
    _publish_and_golden(model.model, "cifar10s_resnet20", dataset=dataset,
                        model_type="cifar_resnet/20",
                        input_shape=[32, 32, 3], num_classes=10, acc=acc,
                        golden_path=GOLDEN_CIFAR, probe_x=probe,
                        golden_target="cifar-golden",
                        input_dtype="uint8")


def regen_cifar_golden() -> None:
    _regen_golden("cifar10s_resnet20", GOLDEN_CIFAR,
                  input_scale=1.0 / 255.0)


def main() -> None:
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.trainer import NNLearner
    from mmlspark_tpu.models.zoo import ModelRepo

    Xtr, ytr, Xte, yte = load_digits_pretrain_split()
    print(f"digits pretrain split: {len(Xtr)} train / {len(Xte)} test")

    learner = NNLearner(arch=ARCH, epochs=40, batch_size=256,
                        learning_rate=0.05, log_every=0, seed=0)
    model = learner.fit(DataFrame({"features": Xtr, "label": ytr}))

    scored = model.transform(DataFrame({"features": Xte, "label": yte}))
    acc = float((np.asarray(scored["scores"]).argmax(axis=1) == yte).mean())
    print(f"test accuracy (classes 0-7): {acc:.4f}")
    if acc < 0.95:
        raise SystemExit(f"refusing to publish a weak model (acc={acc:.3f})")

    fn = model.model  # the trained NNFunction
    meta = ModelRepo(ZOO).publish(
        "digits_resnet8", fn, dataset="sklearn-digits(0-7)",
        model_type="cifar_resnet/8", input_shape=[8, 8, 1], num_classes=8)
    print(f"published {meta.name}: hash={meta.hash[:12]}... -> {meta.uri}")

    # golden fixture: deterministic input -> logits from the published
    # weights (tests compare the zoo-loaded model against this)
    rng = np.random.default_rng(123)
    x = rng.uniform(0, 1, size=(8, 8, 8, 1)).astype(np.float32)
    logits = np.asarray(fn.apply(x), dtype=np.float32)
    os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
    np.savez(GOLDEN, x=x, logits=logits, test_accuracy=acc)
    print(f"golden fixture -> {GOLDEN}")


if __name__ == "__main__":
    target = sys.argv[1] if len(sys.argv) > 1 else "digits"
    if target == "digits":
        # the digits model is tiny and deterministic on the CPU mesh
        from mmlspark_tpu.parallel.topology import use_cpu_devices
        use_cpu_devices(8)
        main()
    elif target == "cifar":
        train_cifar()   # default platform: train on the TPU
    elif target == "cifar-golden":
        from mmlspark_tpu.parallel.topology import use_cpu_devices
        use_cpu_devices(1)   # the test backend
        regen_cifar_golden()
    elif target == "digits32":
        train_digits32()   # REAL data at 32x32; trains on the TPU
    elif target == "digits32-golden":
        from mmlspark_tpu.parallel.topology import use_cpu_devices
        use_cpu_devices(1)   # the test backend
        regen_digits32_golden()
    else:
        raise SystemExit(
            f"unknown target {target!r}; use digits|digits32|cifar")
