"""Train and publish the in-repo model zoo (offline, one-time).

The reference's ModelDownloader serves *trained* CNTK nets
(`ModelDownloader.scala:54,124`); this is the offline converter/trainer
that fills the same role here (SURVEY §7 step 4). It trains
``digits_resnet8`` — a ResNet-8 on sklearn's real 8x8 digits dataset,
classes 0-7 ONLY (8/9 are held out so the transfer-learning example is
genuine: its features were never trained on the target classes) — then
publishes the checkpoint + manifest into ``zoo/`` and writes the
golden-output fixture used by tests/test_zoo.py.

Run from the repo root:  python tools/train_zoo_models.py
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mmlspark_tpu.parallel.topology import use_cpu_devices  # noqa: E402

use_cpu_devices(8)

ZOO = os.path.join(REPO, "zoo")
GOLDEN = os.path.join(REPO, "tests", "resources", "golden_digits_resnet8.npz")
ARCH = {"builder": "cifar_resnet", "depth": 8, "width": 8, "num_classes": 8}


def load_digits_pretrain_split():
    """Digits 0-7, deterministic train/test split (8/9 left for transfer)."""
    from sklearn.datasets import load_digits
    d = load_digits()
    images = (d.images / 16.0).astype(np.float32)[..., None]  # (n, 8, 8, 1)
    labels = d.target.astype(np.int64)
    keep = labels < 8
    images, labels = images[keep], labels[keep]
    rng = np.random.default_rng(0)
    order = rng.permutation(len(images))
    images, labels = images[order], labels[order]
    n_test = 200
    return (images[n_test:], labels[n_test:],
            images[:n_test], labels[:n_test])


def main() -> None:
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.trainer import NNLearner
    from mmlspark_tpu.models.zoo import ModelRepo

    Xtr, ytr, Xte, yte = load_digits_pretrain_split()
    print(f"digits pretrain split: {len(Xtr)} train / {len(Xte)} test")

    learner = NNLearner(arch=ARCH, epochs=40, batch_size=256,
                        learning_rate=0.05, log_every=0, seed=0)
    model = learner.fit(DataFrame({"features": Xtr, "label": ytr}))

    scored = model.transform(DataFrame({"features": Xte, "label": yte}))
    acc = float((np.asarray(scored["scores"]).argmax(axis=1) == yte).mean())
    print(f"test accuracy (classes 0-7): {acc:.4f}")
    if acc < 0.95:
        raise SystemExit(f"refusing to publish a weak model (acc={acc:.3f})")

    fn = model.model  # the trained NNFunction
    meta = ModelRepo(ZOO).publish(
        "digits_resnet8", fn, dataset="sklearn-digits(0-7)",
        model_type="cifar_resnet/8", input_shape=[8, 8, 1], num_classes=8)
    print(f"published {meta.name}: hash={meta.hash[:12]}... -> {meta.uri}")

    # golden fixture: deterministic input -> logits from the published
    # weights (tests compare the zoo-loaded model against this)
    rng = np.random.default_rng(123)
    x = rng.uniform(0, 1, size=(8, 8, 8, 1)).astype(np.float32)
    logits = np.asarray(fn.apply(x), dtype=np.float32)
    os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
    np.savez(GOLDEN, x=x, logits=logits, test_accuracy=acc)
    print(f"golden fixture -> {GOLDEN}")


if __name__ == "__main__":
    main()
